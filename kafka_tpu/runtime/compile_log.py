"""Compile observatory: the record of every XLA compilation (ISSUE 18).

Three observability layers already watch the host side (tracing, the
SLO/roofline plane, the flight recorder) but none of them can answer
the question that dominates a TPU serving incident: *what compiled,
when, and why*.  A rebuild's outage window is compile-bound, a shape
regression shows up as a silent recompile storm mid-traffic, and the
persistent compile cache either saved you minutes or it didn't — all
invisible today.  This module is the device-truth answer for the
compile axis:

* **Bounded ring** — every XLA compilation lands in a fixed-size ring
  (``KAFKA_TPU_COMPILE_RING`` records, default 256; 0 = off with the
  engine byte-identical to an unobserved build — ``instrument`` returns
  the function unchanged and no listener ever registers).  One record =
  one compilation: program label (the engine's ``_FN_CACHE`` tag),
  wall-clock seconds, persistent-cache disposition (``hit`` / ``miss``
  / ``off`` — the ``compile_cache_dir`` wired in ``server/config.py``),
  and the engine phase that triggered it (``boot`` / ``warmup`` /
  ``first_traffic`` / ``rebuild``).

* **Two capture paths** — the primary recorder is a
  ``jax.monitoring`` duration listener filtered on
  ``/jax/core/compile/backend_compile_duration`` (fires once per real
  backend compile, silent on already-compiled calls; cached-same-shape
  dispatches cost nothing).  The engine's compile sites additionally
  wrap their jitted callables with :func:`instrument`, which stamps a
  thread-local label so the listener can attribute the compile — and,
  on runtimes whose monitoring does not emit the event, times the
  first call itself as a wall-clock fallback.  The two paths dedupe:
  when monitoring observed a compile during the instrumented call, the
  fallback stands down.

* **Storm detection** — ``N`` compiles inside ``W`` seconds *after the
  engine reached first traffic* (``KAFKA_TPU_COMPILE_STORM_N`` /
  ``_S``, default 3 in 60s) means shapes are churning while users
  wait.  The condition is level-held here and edge-counted by the
  flight recorder's ``compile_storm`` anomaly; the autoscaler refuses
  to resize while it holds (a rebuild mid-storm doubles the very
  outage it is reacting to).  Boot / warmup / rebuild compiles are the
  expected cost of those phases and never count toward a storm.

``GET /debug/compiles`` serves the ring; the ``compiles`` sections of
``/metrics`` and ``/admin/signals`` carry the totals.  The observatory
is process-wide (XLA compilation is a process-level event — dp
replicas share one cache and one monitoring stream), so the section is
reported once, not per replica.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("kafka_tpu.compile")

RING_ENV = "KAFKA_TPU_COMPILE_RING"
STORM_N_ENV = "KAFKA_TPU_COMPILE_STORM_N"
STORM_S_ENV = "KAFKA_TPU_COMPILE_STORM_S"

# the jax.monitoring event that fires once per real backend compile
# (probed on jax 0.4.37; silent for cached-executable calls)
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# fired per compile request when the persistent cache is enabled; the
# presence of a cache *hit* event marks the in-flight label as "hit"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

PHASES = ("boot", "warmup", "first_traffic", "rebuild")

# one compile above this many seconds is always worth a log line
_SLOW_COMPILE_S = 30.0


def ring_default() -> int:
    """KAFKA_TPU_COMPILE_RING with nonsense clamped to the default
    (256 records outlives any realistic warmup + rebuild history)."""
    raw = os.environ.get(RING_ENV)
    if raw is None or raw == "":
        return 256
    try:
        return max(0, int(raw))
    except ValueError:
        return 256


def _env_pos(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


class CompileObservatory:
    """Process-wide compile ring + storm detector.

    Writes arrive from whichever thread jax compiles on (engine thread,
    warmup executor, rebuild executor) under ``_lock``; reads
    (``/debug/compiles``, metrics, signals) take the same lock — the
    ring is tiny and compiles are rare, so contention is irrelevant.
    """

    def __init__(self, size: int):
        if size <= 0:
            raise ValueError("CompileObservatory size must be > 0 "
                             "(0 = off means: do not construct one)")
        self.size = size
        self._lock = threading.Lock()
        self._ring: List[Dict[str, Any]] = []
        self.next_seq = 0
        self.phase = "boot"
        self.cache_dir: Optional[str] = None  # set by configure_cache
        # totals (monotone counters)
        self.compiles_total = 0
        self.compile_seconds_total = 0.0
        self.by_cache: Dict[str, int] = {"hit": 0, "miss": 0, "off": 0}
        self.by_phase: Dict[str, int] = {p: 0 for p in PHASES}
        # storm detector: wall times of first_traffic-phase compiles
        self.storm_n = max(1, int(_env_pos(STORM_N_ENV, 3)))
        self.storm_s = _env_pos(STORM_S_ENV, 60.0)
        self._storm_times: List[float] = []
        self.storms_total = 0
        self._storm_was_active = False
        # thread-local label context set by instrument() wrappers so the
        # monitoring listener can attribute the compile it observes
        self._tls = threading.local()

    # -- label context (instrument wrappers) -----------------------------

    def _push_label(self, label: str) -> None:
        self._tls.label = label
        self._tls.observed = False

    def _pop_label(self) -> bool:
        observed = getattr(self._tls, "observed", False)
        self._tls.label = None
        self._tls.observed = False
        return observed

    def _current_label(self) -> Optional[str]:
        return getattr(self._tls, "label", None)

    # -- recording -------------------------------------------------------

    def record(self, label: str, seconds: float,
               cache: Optional[str] = None,
               now: Optional[float] = None) -> None:
        """One compilation happened.  ``cache`` defaults from the
        persistent-cache configuration: ``off`` when no cache dir is
        configured, ``miss`` otherwise (a hit is marked explicitly by
        the cache-hit listener)."""
        now = time.time() if now is None else now
        if cache is None:
            cache = "miss" if self.cache_dir else "off"
        with self._lock:
            rec = {
                "seq": self.next_seq,
                "t": round(now, 3),
                "label": label,
                "seconds": round(seconds, 4),
                "cache": cache,
                "phase": self.phase,
            }
            if len(self._ring) < self.size:
                self._ring.append(rec)
            else:
                self._ring[self.next_seq % self.size] = rec
            self.next_seq += 1
            self.compiles_total += 1
            self.compile_seconds_total += seconds
            self.by_cache[cache] = self.by_cache.get(cache, 0) + 1
            self.by_phase[self.phase] = self.by_phase.get(
                self.phase, 0) + 1
            if self.phase == "first_traffic":
                self._storm_times.append(now)
                # bound the storm window list (ring discipline)
                if len(self._storm_times) > 4 * self.storm_n:
                    del self._storm_times[: -2 * self.storm_n]
                if (self._storm_active_locked(now)
                        and not self._storm_was_active):
                    self._storm_was_active = True
                    self.storms_total += 1
                    logger.warning(
                        "compile storm: %d compiles in %.0fs while "
                        "serving (last: %s, %.2fs)", self.storm_n,
                        self.storm_s, label, seconds)
        if seconds >= _SLOW_COMPILE_S:
            logger.warning("slow compile: %s took %.1fs (phase=%s, "
                           "cache=%s)", label, seconds, self.phase,
                           cache)
        else:
            logger.info("compile: %s %.2fs (phase=%s, cache=%s)",
                        label, seconds, self.phase, cache)

    def mark_cache_hit(self) -> None:
        """The persistent cache served the in-flight compile (seen via
        the cache-hit monitoring event).  Rewrites the most recent
        record for the current label context, or records a zero-cost
        hit if the backend-compile event never fired (a true hit skips
        backend compilation entirely on some runtimes)."""
        label = self._current_label() or "?"
        with self._lock:
            for rec in reversed(self._ring):
                if rec["label"] == label and rec["cache"] != "hit":
                    self.by_cache[rec["cache"]] -= 1
                    rec["cache"] = "hit"
                    self.by_cache["hit"] = self.by_cache.get(
                        "hit", 0) + 1
                    return
        self.record(label, 0.0, cache="hit")

    # -- storm -----------------------------------------------------------

    def _storm_active_locked(self, now: float) -> bool:
        cutoff = now - self.storm_s
        n = 0
        for t in reversed(self._storm_times):
            if t < cutoff:
                break
            n += 1
        return n >= self.storm_n

    def storm_active(self, now: Optional[float] = None) -> bool:
        """Level-held storm condition (the flight recorder edge-counts
        it; the autoscaler vetoes resizes while it holds)."""
        now = time.time() if now is None else now
        with self._lock:
            active = self._storm_active_locked(now)
            if not active:
                self._storm_was_active = False
            return active

    # -- export ----------------------------------------------------------

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            if len(self._ring) < self.size:
                return [dict(r) for r in self._ring]
            start = self.next_seq % self.size
            return [dict(self._ring[(start + i) % self.size])
                    for i in range(self.size)]

    def metrics_section(self) -> Dict[str, Any]:
        """The ``compiles`` section of the metrics snapshot (keys
        registered as COMPILE_METRIC_KEYS in metrics.py)."""
        now = time.time()
        storm = self.storm_active(now)
        with self._lock:
            return {
                "compiles_total": self.compiles_total,
                "compile_seconds_total": round(
                    self.compile_seconds_total, 4),
                "compile_storm_active": 1 if storm else 0,
                "compile_storms_total": self.storms_total,
                "by_cache": dict(self.by_cache),
                "by_phase": dict(self.by_phase),
            }

    def signals_section(self) -> Dict[str, Any]:
        """The ``compiles`` section of /admin/signals: ring summary +
        the storm flag the autoscaler contract keys on."""
        sec = self.metrics_section()
        with self._lock:
            recent = [dict(r) for r in self._ring[-8:]] \
                if len(self._ring) < self.size else None
            if recent is None:
                start = self.next_seq % self.size
                recent = [dict(self._ring[(start + i) % self.size])
                          for i in range(self.size)][-8:]
            sec.update({
                "ring_size": self.size,
                "next_seq": self.next_seq,
                "phase": self.phase,
                "cache_dir": self.cache_dir,
                "storm_n": self.storm_n,
                "storm_window_s": self.storm_s,
                "recent": recent,
            })
        sec["storm_active"] = bool(sec.pop("compile_storm_active"))
        return sec

    def snapshot(self) -> Dict[str, Any]:
        """Full ring for GET /debug/compiles."""
        sec = self.metrics_section()
        return {
            "ring_size": self.size,
            "next_seq": self.next_seq,
            "phase": self.phase,
            "cache_dir": self.cache_dir,
            "storm": {
                "active": bool(sec["compile_storm_active"]),
                "storms_total": self.storms_total,
                "n": self.storm_n,
                "window_s": self.storm_s,
            },
            "totals": {
                "compiles": sec["compiles_total"],
                "seconds": sec["compile_seconds_total"],
                "by_cache": sec["by_cache"],
                "by_phase": sec["by_phase"],
            },
            "records": self.records(),
        }


# ---------------------------------------------------------------------------
# module-level singleton: XLA compilation is process-global, so is this

_OBS: Optional[CompileObservatory] = None
_LISTENERS_REGISTERED = False
_INIT_LOCK = threading.Lock()


def _on_duration_event(event: str, duration_s: float, **kw: Any) -> None:
    obs = _OBS
    if obs is None or event != _COMPILE_EVENT:
        return
    label = obs._current_label()
    if label is not None:
        obs._tls.observed = True
    try:
        obs.record(label or "?", duration_s)
    except Exception:  # pragma: no cover - never break a compile
        logger.debug("compile record failed", exc_info=True)


def _on_event(event: str, **kw: Any) -> None:
    obs = _OBS
    if obs is None or event != _CACHE_HIT_EVENT:
        return
    try:
        obs.mark_cache_hit()
    except Exception:  # pragma: no cover - never break a compile
        logger.debug("cache-hit record failed", exc_info=True)


def _register_listeners() -> bool:
    """Hook jax.monitoring once per process (there is no public
    unregister-by-callback; the listeners are no-ops while _OBS is
    None, so enable/disable is just the singleton swap)."""
    global _LISTENERS_REGISTERED
    if _LISTENERS_REGISTERED:
        return True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(
            _on_duration_event)
        monitoring.register_event_listener(_on_event)
        _LISTENERS_REGISTERED = True
        return True
    except Exception:  # pragma: no cover - monitoring API drift
        logger.info("jax.monitoring unavailable; compile observatory "
                    "falls back to instrument() wall timing")
        return False


def enabled() -> bool:
    return _OBS is not None


def get() -> Optional[CompileObservatory]:
    return _OBS


def init(size: Optional[int] = None) -> Optional[CompileObservatory]:
    """Build (or return) the process observatory.  size 0 disables —
    nothing is constructed and every hook below is a no-op returning
    its input, keeping the disabled build byte-identical."""
    global _OBS
    size = ring_default() if size is None else size
    if size <= 0:
        return _OBS
    with _INIT_LOCK:
        if _OBS is None:
            _OBS = CompileObservatory(size)
            _register_listeners()
        return _OBS


def reset_for_tests() -> None:
    """Drop the singleton (listeners stay registered as no-ops)."""
    global _OBS
    _OBS = None


def set_phase(phase: str) -> None:
    """Engine lifecycle transition (boot -> warmup -> first_traffic,
    with rebuild excursions).  Unknown names are kept verbatim so a
    future phase shows up in the ring rather than vanishing."""
    obs = _OBS
    if obs is not None:
        obs.phase = phase


def get_phase() -> Optional[str]:
    obs = _OBS
    return obs.phase if obs is not None else None


def configure_cache(cache_dir: Optional[str]) -> None:
    """Tell the observatory whether a persistent compile cache is in
    play (decides the default cache disposition: off vs miss)."""
    obs = _OBS
    if obs is not None:
        obs.cache_dir = cache_dir or None


def instrument(label: str, fn: Callable[..., Any]) -> Callable[..., Any]:
    """Wrap a freshly-jitted callable at its ``_FN_CACHE`` miss site.

    Disabled (ring 0): returns ``fn`` unchanged — the dispatch path is
    byte-identical to an uninstrumented build.  Enabled: every call
    stamps the thread-local label (so recompiles triggered by NEW
    input shapes attribute correctly too, not just the first call) and
    the first call doubles as a wall-clock fallback recorder for
    runtimes whose jax.monitoring never emits the compile event.
    """
    obs = _OBS
    if obs is None:
        return fn

    state = {"first": True}

    def wrapper(*args: Any, **kwargs: Any) -> Any:
        o = _OBS
        if o is None:
            return fn(*args, **kwargs)
        o._push_label(label)
        t0 = time.monotonic()
        try:
            return fn(*args, **kwargs)
        finally:
            dt = time.monotonic() - t0
            observed = o._pop_label()
            if state["first"]:
                state["first"] = False
                if not observed:
                    # monitoring stayed silent for a first call that
                    # necessarily traced + compiled: record wall time
                    o.record(label, dt)

    wrapper.__name__ = f"compile_log[{label}]"
    wrapper.__wrapped__ = fn  # tests / introspection
    return wrapper
