"""Thread-keyed KV prefix cache over the refcounted page pool.

BASELINE config 2: multi-turn threads re-serve the same conversation prefix
every turn; without this, every request re-prefills from token zero.  The
reference has the persistence half of the story (the thread store is the
recovery log, src/db/supabase.py:100-175) — this is the cache optimization
the TPU engine layers on top:

* When a request carrying a ``prefix_key`` (the thread id) finishes, its
  sequence's pages are **retained** into the cache together with the exact
  token ids materialized in them.
* The next request with the same key shares the longest common token-prefix
  at page granularity: full pages are refcount-shared (never re-written —
  new tokens only ever write pages at or past the first partial page), and
  prefill resumes at the shared boundary (`SequencePages.length > 0`, which
  the engine's chunked prefill already supports).
* Entries are LRU; the engine evicts them under page pressure before it
  preempts live requests — a cache entry is always strictly cheaper to
  rebuild (one prefill) than a preempted request (prefill + lost batch
  slot).

Sharing is safe with the engine's async pipeline: a retiring request's
in-flight decode steps only write KV at positions >= the stored token
count, which land in the first partial (unshared) page or later.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from .kv_cache import PagePool


@dataclasses.dataclass
class _Entry:
    tokens: List[int]  # token ids whose KV the pages hold, in order
    pages: List[int]   # physical pages (cache holds one retain on each)


class PrefixCache:
    """LRU map: prefix_key -> (tokens, retained pages)."""

    def __init__(self, pool: PagePool, max_entries: int = 64):
        self.pool = pool
        self.max_entries = max_entries
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        # counters (observability + tests)
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0

    def __len__(self) -> int:
        return len(self._entries)

    def page_owners(self) -> Dict[int, int]:
        """Per-page retain counts held by cache entries (engine
        self_check: these are legitimate owners alongside live
        sequences)."""
        owners: Dict[int, int] = {}
        for e in self._entries.values():
            for p in e.pages:
                owners[p] = owners.get(p, 0) + 1
        return owners

    def lookup(
        self, key: str, prompt_ids: Sequence[int]
    ) -> Optional[Tuple[List[int], int]]:
        """Return (retained shared pages, cached token count) or None.

        The caller owns one retain on each returned page (released through
        the sequence's normal free path).  Only whole pages are shared, and
        at least one prompt token is always left to prefill — the prefill
        must produce last-token logits.
        """
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        lcp = 0
        limit = min(len(entry.tokens), len(prompt_ids) - 1)
        while lcp < limit and entry.tokens[lcp] == prompt_ids[lcp]:
            lcp += 1
        shared_pages = lcp // self.pool.page_size
        if shared_pages == 0:
            self.misses += 1
            return None
        pages = list(entry.pages[:shared_pages])
        self.pool.retain(pages)
        self.hits += 1
        cached = shared_pages * self.pool.page_size
        self.tokens_reused += cached
        return pages, cached

    def store(self, key: str, tokens: Sequence[int], pages: Sequence[int]) -> None:
        """Retain `pages` under `key`; replaces any previous entry."""
        old = self._entries.pop(key, None)
        if old is not None:
            self.pool.release(old.pages)
        n_pages = min(len(pages), -(-len(tokens) // self.pool.page_size))
        kept = list(pages[:n_pages])
        self.pool.retain(kept)
        self._entries[key] = _Entry(tokens=list(tokens), pages=kept)
        while len(self._entries) > self.max_entries:
            self._evict_one()

    def _evict_one(self) -> bool:
        if not self._entries:
            return False
        _, entry = self._entries.popitem(last=False)
        self.pool.release(entry.pages)
        return True

    def reclaim(self, pages_needed: int) -> bool:
        """Evict LRU entries until the pool can satisfy `pages_needed`.

        Released pages only become free when no live sequence shares them,
        so eviction is attempted entry-by-entry and may legitimately fail.
        """
        while self.pool.free_pages < pages_needed:
            if not self._evict_one():
                return False
        return True

    def invalidate(self, key: str) -> None:
        entry = self._entries.pop(key, None)
        if entry is not None:
            self.pool.release(entry.pages)

    def clear(self) -> None:
        while self._evict_one():
            pass
