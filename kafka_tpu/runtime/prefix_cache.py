"""Content-addressed radix-tree KV prefix cache over the refcounted page pool.

BASELINE configs 2 and 3: multi-turn threads re-serve the same conversation
prefix every turn, and in a fan-out-heavy agent deployment *every* thread
begins with the same system prompt + tool schemas — often thousands of
tokens.  The original cache here was an exact `prefix_key` (thread id) LRU:
it reused a thread's *own* prior turn but re-prefilled the shared
system/tool prefix once per thread, per replica.  This version is a radix
tree over page-granular token runs (SGLang's RadixAttention; page sharing
a la vLLM's PagedAttention): `lookup()` walks the tree for the longest
cached prefix regardless of which thread wrote it, so the shared prefix
prefills once per *replica*.

Mechanics:

* Nodes hold page-aligned token runs plus the physical pages backing them
  (the cache holds exactly one retain per stored page).  Children are keyed
  by their first *page* of tokens — sequences diverging mid-page therefore
  have different keys and never share the divergent page, which keeps every
  shared page byte-exact.
* `store()` inserts a finished sequence's materialized tokens along its
  token path: matched runs are descended (the cache keeps its existing
  pages — the incoming duplicates are simply not retained), divergence
  splits a node at the page boundary, and the unmatched suffix becomes a
  new node whose pages are retained.
* `lookup()` shares only whole pages and always leaves at least one prompt
  token to prefill (the prefill must produce last-token logits).  The
  copy-on-write invariant is preserved by the engine's existing rule: new
  tokens only ever write pages at or past the first partial page, so a
  shared full page is never re-written by the reusing sequence.
* Eviction is leaf-LRU: under page pressure (`reclaim`) or the page budget
  (`max_pages`, env `KAFKA_TPU_PREFIX_CACHE_PAGES` through the serving
  config) the least-recently-used *leaf* releases its pages — shared
  prefixes near the root survive their coldest consumer.  Evicting a cache
  node is still strictly cheaper than preempting a live request (one
  prefill vs prefill + a lost batch slot), so the engine reclaims here
  before it ever preempts.
* `invalidate(thread_id)` drops only the nodes no *other* thread's store
  path claims, so deleting one thread never cold-starts its siblings.
* With a KV tier attached (runtime/kv_tier.py, ISSUE 9), eviction
  **demotes** instead of dropping: the node's pages are copied to the
  host tier and the node stays in the tree as a *host-resident* run
  (``pages == []``, ``host_run`` set).  A later ``lookup()`` crossing it
  allocates fresh pool pages and promotes the run back
  (``source="host_tier"``) — a returning thread re-materializes its KV
  instead of re-prefilling it.  ``store()`` descending a host-resident
  run with matching tokens *adopts* the incoming sequence's pages — a
  free promotion.  A failed promote removes the node subtree and the hit
  truncates at that boundary: degrade to re-prefill, never partial KV.
  ``match_tokens`` counts host-resident runs as matchable, so the DP
  router treats a host-tier prefix as routable affinity.

Sharing is safe with the engine's async pipeline: a retiring request's
in-flight decode steps only write KV at positions >= the stored token
count, which land in the first partial (unshared) page or later.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from .kv_cache import OutOfPagesError, PagePool


@dataclasses.dataclass
class PrefixHit:
    """One successful lookup: the caller owns one retain on each page."""

    pages: List[int]
    tokens: int  # cached token count (= len(pages) * page_size)
    # "own" (this thread stored through here) | "cross" (another thread's
    # shared prefix) | "host_tier" (any part was promoted from the tier)
    # | "object_tier" (any part was woken from the shared object store)
    # | "shipped" (any part arrived via cross-replica page shipping)
    source: str
    # tokens of the hit that were re-materialized from the host/disk tier
    promoted_tokens: int = 0
    # tokens of the hit re-materialized from the shared OBJECT store —
    # a dormant thread waking on a replica that never served it
    object_tokens: int = 0


# Per-node claim cap: a fan-out shared-prefix node is stored through by
# EVERY thread, and claims must not grow host memory unboundedly on a
# long-lived replica (the router's affinity LRU is capped for the same
# reason).  Dropping the oldest claim is conservative: the node merely
# reads as "cross" for (and survives invalidate by) a thread that hasn't
# stored through it recently — exactly how a genuinely shared node behaves.
_KEYS_CAP = 512


class _Node:
    """One page-aligned token run.  Device-resident: len(tokens) ==
    len(pages) * page_size.  Host-resident (KV tier): pages is empty and
    `host_run` names the demoted payload — tokens are kept so the radix
    walk still matches through it."""

    __slots__ = ("tokens", "pages", "children", "parent", "keys",
                 "host_run", "shipped", "woken")

    def __init__(
        self,
        tokens: List[int],
        pages: List[int],
        parent: Optional["_Node"],
    ):
        self.tokens = tokens
        self.pages = pages
        # first-page token tuple -> child (mid-page divergence => distinct
        # first pages => distinct keys; splits stay page-aligned)
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.parent = parent
        # prefix_keys whose store() path includes this node, recency-
        # ordered and capped (invalidate removes only nodes nobody else
        # claims; `in` answers own/cross classification)
        self.keys: "OrderedDict[str, None]" = OrderedDict()
        # KV-tier run id when demoted (host/disk resident), else None
        self.host_run: Optional[str] = None
        # True while this run's pages arrived via cross-replica page
        # shipping (disaggregated prefill/decode) and no local thread
        # has stored through it yet: the first lookup crossing it
        # classifies as cache_source="shipped" (the zero-re-prefill
        # proof), and a normal store() descending it clears the marker.
        self.shipped = False
        # True while this run's pages were re-materialized from the
        # shared OBJECT store (a sleep-manifest wake) and no local thread
        # has stored through it since: lookups crossing it classify as
        # cache_source="object_tier" — the cross-host wake proof.
        self.woken = False

    def n_pages(self, page_size: int) -> int:
        """Run length in pages regardless of residency."""
        return len(self.tokens) // page_size


class PrefixCache:
    """Radix tree: token path -> retained pages, shared across threads."""

    def __init__(self, pool: PagePool, max_pages: Optional[int] = None,
                 tier=None):
        self.pool = pool
        # Page budget for retained pages (None = bounded only by pool
        # pressure via reclaim()).  Replaces the old entry-count cap: pages
        # are what the pool actually runs out of.
        self.max_pages = max_pages
        # Optional KV tier manager (runtime/kv_tier.KVTierManager): when
        # set, eviction demotes page runs host-side instead of dropping
        # them, and lookups promote them back.  None = the pre-tier
        # behavior, byte-identical.
        self.tier = tier
        self._root = _Node([], [], None)
        # running shape counters (store() at budget must not re-walk the
        # tree per evicted leaf — that is O(nodes^2) on the engine thread)
        self._n_nodes = 0
        self._n_pages = 0
        # leaves in (approximate) recency order: eviction pops the front in
        # O(1) instead of a full-tree scan per reclaimed leaf — reclaim()
        # runs on the engine thread's allocation path.  Approximate: a
        # node that BECOMES a leaf (split / child removal) re-enters at
        # the back; true recency is restored on its next touch.
        self._leaves: "OrderedDict[_Node, None]" = OrderedDict()
        # Set once any node's claim list hits _KEYS_CAP and drops a key:
        # the dropped key's deeper nodes may still claim it, breaking the
        # root-anchored invariant invalidate()'s fast path walks — it then
        # degrades to a full-tree sweep (tree size is page-bounded).
        self._claims_capped = False
        # Incremental page -> retain-count index mirroring the tree's
        # holdings.  Two consumers: page_owners() (engine self_check) no
        # longer walks the tree, and owns_any() answers the speculative-
        # decoding write-span invariant ("verify writes never touch
        # radix-shared pages") in O(span) per dispatch.
        self._page_retains: Dict[int, int] = {}
        # Content generation: bumped whenever the set of cached (token,
        # page) runs changes (store of new pages, any eviction/removal).
        # The DP router's probe memoization keys its per-replica
        # match_tokens results on this — an unchanged generation means an
        # identical radix walk result for an identical prompt head.
        self.generation = 0
        # KV-tier shape counters (gauges; the tier manager owns the
        # demote/promote traffic counters)
        self._host_nodes = 0
        self._host_pages = 0
        # counters (observability + tests)
        self.hits = 0
        self.misses = 0
        self.tokens_reused = 0
        self.cross_thread_hits = 0  # hits whose deepest node another thread wrote
        self.host_tier_hits = 0  # hits that promoted at least one tier run
        self.shipped_hits = 0  # hits crossing a cross-replica-shipped run
        self.object_tier_hits = 0  # hits crossing an object-store-woken run
        self.evictions = 0  # nodes evicted under pressure (leaf-LRU + budget)
        self.pages_evicted = 0
        self.probes = 0  # read-only match_tokens walks (router memo tests)

    # -- introspection ---------------------------------------------------

    def _iter_nodes(self) -> Iterator[_Node]:
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def __len__(self) -> int:
        """Node count (the old per-thread entry count's closest analogue)."""
        return self._n_nodes

    @property
    def total_pages(self) -> int:
        """HBM pool pages the cache currently retains (gauge for
        /metrics; host-resident runs are counted by host_pages)."""
        return self._n_pages

    @property
    def host_nodes(self) -> int:
        """Radix nodes currently demoted to the KV tier (gauge)."""
        return self._host_nodes

    @property
    def host_pages(self) -> int:
        """Page-equivalents currently demoted to the KV tier (gauge)."""
        return self._host_pages

    def page_owners(self) -> Dict[int, int]:
        """Per-page retain counts held by the tree (engine self_check:
        these are legitimate owners alongside live sequences).  Served
        from the incremental index — O(cached pages), no tree walk."""
        return dict(self._page_retains)

    def owns_any(self, pages: Sequence[int]) -> bool:
        """Does the cache retain ANY of `pages`?  O(len(pages)) probe for
        the speculative-decoding invariant (engine._assert_private_tail):
        verify-step writes must never land in a radix-cached page."""
        return any(p in self._page_retains for p in pages)

    def _retain_pages(self, pages: Sequence[int]) -> None:
        self.pool.retain(pages)
        for p in pages:
            self._page_retains[p] = self._page_retains.get(p, 0) + 1

    def _release_pages(self, pages: Sequence[int]) -> None:
        self.pool.release(pages)
        for p in pages:
            left = self._page_retains.get(p, 0) - 1
            if left <= 0:
                self._page_retains.pop(p, None)
            else:
                self._page_retains[p] = left

    def _claim(self, node: _Node, key: str) -> None:
        node.keys[key] = None
        node.keys.move_to_end(key)
        while len(node.keys) > _KEYS_CAP:
            node.keys.popitem(last=False)
            self._claims_capped = True

    def _touch(self, node: _Node) -> None:
        """Refresh recency.  The _leaves OrderedDict IS the LRU state —
        only leaves are eviction candidates, so touching a non-leaf is a
        no-op by design."""
        if node in self._leaves:
            self._leaves.move_to_end(node)

    # -- lookup ----------------------------------------------------------

    def _walk(
        self, prompt_ids: Sequence[int]
    ) -> Tuple[List[Tuple[_Node, int]], int, _Node]:
        """Longest whole-page cached match for `prompt_ids` (read-only).

        Returns (segments, matched_pages, deepest_node) where segments is
        the matched (node, pages_taken) chain — nodes may be device- or
        host-resident (lookup() promotes the latter).  At least one prompt
        token is always left to prefill, so at most (len-1)//page_size
        pages are matchable.
        """
        ps = self.pool.page_size
        limit = (len(prompt_ids) - 1) // ps
        node = self._root
        segments: List[Tuple[_Node, int]] = []
        matched = 0
        while matched < limit:
            key = tuple(prompt_ids[matched * ps:(matched + 1) * ps])
            child = node.children.get(key)
            if child is None:
                break
            n = child.n_pages(ps)
            take = 1  # the child key IS its first page: already matched
            while (
                take < n
                and matched + take < limit
                and child.tokens[take * ps:(take + 1) * ps]
                == list(prompt_ids[(matched + take) * ps:(matched + take + 1) * ps])
            ):
                take += 1
            segments.append((child, take))
            matched += take
            node = child
            if take < n:
                break
        return segments, matched, node

    def match_tokens(self, prompt_ids: Sequence[int]) -> int:
        """Longest cached prefix in TOKENS — a read-only probe (no retains,
        no LRU touch, no hit/miss counters; `probes` only counts walks so
        the router's memoization is testable).  The DP router scores
        replicas with this so cold threads land where their system prompt
        is already hot (runtime/dp_router.py _pick)."""
        self.probes += 1
        _, matched, _ = self._walk(prompt_ids)
        return matched * self.pool.page_size

    def lookup(
        self, key: str, prompt_ids: Sequence[int]
    ) -> Optional[PrefixHit]:
        """Longest cached prefix for `prompt_ids`, whoever wrote it.

        The caller owns one retain on each returned page (released through
        the sequence's normal free path).  `key` only classifies the hit:
        "own" when this thread's own store path covers the match, "cross"
        when another thread's prefix is being reused, "host_tier" when any
        part of the match was promoted back from the KV tier.

        Host-resident runs along the match are promoted here: fresh pool
        pages are allocated and the H2D copy is enqueued (ahead of the
        caller's suffix prefill, so it overlaps).  A promotion that cannot
        get pages — or whose run the tier lost — truncates the hit at that
        boundary; a torn promote additionally removes the node subtree
        (its pages were freed, nothing is shared yet: re-prefill, never
        partial KV).
        """
        segments, matched, _ = self._walk(prompt_ids)
        if (
            key is not None
            and self.tier is not None
            and getattr(self.tier, "object", None) is not None
        ):
            # Sleep-manifest wake (ISSUE 14): when the shared object
            # store knows this thread beyond what the local tree holds,
            # fetch its runs, import them into fresh pages and insert
            # them — the dormant thread wakes on THIS replica whether or
            # not it ever served here.
            if self._wake_from_object(key, prompt_ids, matched,
                                      {n for n, _ in segments}):
                segments, matched, _ = self._walk(prompt_ids)
        if matched == 0:
            self.misses += 1
            return None
        ps = self.pool.page_size
        pages: List[int] = []
        promoted = 0
        object_tok = 0
        shipped_any = False
        last_node: Optional[_Node] = None
        # nodes of this walk must not be evicted by promotion's reclaim —
        # their pages are in `pages` but not yet retained by the caller
        protect = {node for node, _ in segments}
        for node, take in segments:
            if node.host_run is not None:
                if self.tier is None:
                    break  # unreachable by construction; fail soft
                self.tier.touch(node.host_run)
                if not self._promote_node(node, protect):
                    break
                promoted += take * ps
            if node.shipped:
                shipped_any = True
            if node.woken:
                object_tok += take * ps
            pages.extend(node.pages[:take])
            last_node = node
        if last_node is None:
            self.misses += 1
            return None
        # refresh recency: only the deepest matched node can be a leaf
        # (its ancestors have children by construction), so one touch
        # keeps hot prefixes off the eviction front
        self._touch(last_node)
        self.pool.retain(pages)
        cached = len(pages) * ps
        if shipped_any:
            # runs shipped from a prefill-pool replica: the thread's
            # zero-re-prefill admission on the decode pool is provable
            # from this classification (disaggregated serving)
            source = "shipped"
        elif object_tok:
            # runs woken from the shared object store: the cross-host
            # resume-without-re-prefill is provable from this
            source = "object_tier"
        elif promoted:
            source = "host_tier"
        elif key is not None and key in last_node.keys:
            source = "own"
        else:
            source = "cross"
        return PrefixHit(pages=pages, tokens=cached, source=source,
                         promoted_tokens=promoted,
                         object_tokens=object_tok)

    def _wake_from_object(self, key: str, prompt_ids: Sequence[int],
                          matched: int, protect) -> bool:
        """Re-materialize a dormant thread from its sleep manifest.

        The manifest's runs beyond the locally-matched boundary are
        fetched from the shared store, imported into freshly-allocated
        pool pages (one contiguous alloc), and inserted into the radix
        tree via store() — dummy page ids stand in for the local prefix,
        which store() descends without touching.

        The wake TRUNCATES at the first ABSENT object (cheap head
        probes, before any paging work): organically-written manifests
        legitimately name ancestor runs that are still device-resident
        on the sleeping host and not archived yet, and runs past a
        missing one are unusable anyway (their prefix is the hole).
        Over the present runs it is ALL-OR-NOTHING: a failed get of a
        present object, size mismatch, or torn import frees every page
        allocated for the wake and aborts it — the request degrades to
        the local (disk-tier-or-less) hit, never partial KV.  Pages are
        reserved BEFORE the payload fetches, so pool pressure aborts
        without wasting store round-trips.  Returns True when at least
        one run was woken (the caller re-walks)."""
        from .tracing import record_span

        obj = self.tier.object
        ps = self.pool.page_size
        limit = (len(prompt_ids) - 1) // ps  # max matchable pages
        if matched >= limit:
            return False
        man = obj.read_manifest(key)
        if man is None:
            return False
        toks = man.get("tokens") or []
        runs = man.get("runs") or []
        # verified page-aligned agreement between manifest and prompt
        m = 0
        stop = min(len(toks), limit * ps)
        while m < stop and toks[m] == prompt_ids[m]:
            m += 1
        man_pages = m // ps
        if man_pages <= matched:
            return False
        t0 = time.monotonic()
        # select the manifest runs beyond the local boundary (contiguous
        # from it; a run straddling the boundary means the local tree
        # split differently than the sleeping host's — abort, the local
        # hit stands)
        wake: List[Tuple[int, str]] = []  # (n_pages, run_key)
        off = 0
        for r in runs:
            n = int(r.get("tokens", 0)) // ps
            if n <= 0:
                return False  # malformed manifest
            if off + n <= matched:
                off += n
                continue
            if off < matched or off + n > man_pages:
                break
            if not r.get("key") or not obj.has_run(r["key"]):
                # absent object (an organically-manifested ancestor not
                # archived yet, or budget-evicted content): truncate —
                # deeper runs are unusable without this prefix
                break
            wake.append((n, r["key"]))
            off += n
        if not wake:
            return False
        # reserve the destination pages BEFORE fetching payloads: pool
        # pressure must abort without paying store round-trips
        total_pages = sum(n for n, _ in wake)
        if self.pool.free_pages < total_pages:
            self._reclaim_protected(total_pages, protect)
        try:
            pages = self.pool.alloc(total_pages)
        except OutOfPagesError:
            return False
        nbytes = 0
        pos = 0
        # fetch_run consumes payloads the wake prefetcher staged at
        # submit time (ISSUE 19); without a prefetcher (or on a bare
        # test tier predating it) it IS get_run
        fetch = getattr(obj, "fetch_run", None) or obj.get_run
        pre = getattr(obj, "prefetcher", None)
        if pre is not None and len(wake) > 1:
            # multi-run wake: stage every run NOW so the store GETs run
            # in parallel on the prefetcher pool and the loop below
            # consumes them in order — the wake pays ~one RTT instead of
            # len(wake).  Single-flight with any router-kicked prefetch;
            # a full staging budget degrades per-run to the serial fetch.
            pre.stage_runs([rkey for _, rkey in wake], key)
        try:
            for n, rkey in wake:
                got = fetch(rkey)
                if got is None or got[2] != n:
                    # failed get of a PRESENT object (torn fetch, lost
                    # between head and get) or a payload whose span
                    # disagrees with the manifest: free EVERY wake page
                    # and keep the local hit.  A miss already counted in
                    # get_run; a span mismatch must not stay invisible.
                    if got is not None:
                        obj.object_get_failures += 1
                    self.pool.release(pages)
                    return False
                k_l, v_l, _, got_bytes = got
                nbytes += got_bytes
                self.tier.shipper.import_run(k_l, v_l, n,
                                             pages[pos:pos + n])
                pos += n
        except Exception:
            # torn import: free EVERY wake page (freshly allocated,
            # shared with nobody — complete cleanup), keep the local hit
            self.pool.release(pages)
            obj.object_get_failures += 1
            return False
        end = (matched + total_pages) * ps
        self.store(key, list(prompt_ids[:end]),
                   [-1] * matched + list(pages), woken=True)
        self.pool.release(pages)  # store() retained what it kept
        woken_tokens = total_pages * ps
        obj.wake_threads += 1
        obj.wake_tokens += woken_tokens
        record_span(
            self.tier.trace_ctx, "thread.wake", time.monotonic() - t0,
            attrs={"tokens": woken_tokens, "runs": len(wake),
                   "bytes": nbytes, "source": "object_tier"},
        )
        return True

    def _promote_node(self, node: _Node, protect) -> bool:
        """Re-materialize a host-resident run into fresh pool pages.

        Under page pressure, promotion reclaims OTHER leaves first —
        demoting a cold run to re-materialize the returning hot one is
        the tier's whole policy — but never a node of the current walk
        (`protect`): those pages are in the hit being assembled and not
        yet retained by the caller, so evicting one would free pages out
        from under the hit.  On tier failure the node subtree is removed
        (the run is gone; deeper nodes are unreachable KV) and the caller
        degrades to re-prefill.
        """
        assert self.tier is not None and node.host_run is not None
        n = node.n_pages(self.pool.page_size)
        if self.pool.free_pages < n:
            self._reclaim_protected(n, protect)
        try:
            new_pages = self.pool.alloc(n)
        except OutOfPagesError:
            return False  # hit truncates; the node stays host-resident
        if not self.tier.promote(node.host_run, new_pages):
            self.pool.release(new_pages)
            self._remove_subtree(node)
            return False
        node.host_run = None
        node.pages = new_pages
        for p in new_pages:
            # alloc's refcount 1 IS the cache's retain — index it without
            # a second pool.retain
            self._page_retains[p] = self._page_retains.get(p, 0) + 1
        self._n_pages += n
        self._host_pages -= n
        self._host_nodes -= 1
        if not node.children:
            self._leaves[node] = None
            self._leaves.move_to_end(node)
        return True

    def commit_hit(self, tokens: int, source: Optional[str]) -> None:
        """Count one hit.  Deliberately NOT done inside lookup(): these
        counters export as a Prometheus counter family (monotone by
        contract), and a page-blocked admission re-runs lookup every
        scheduler iteration — counting there would either inflate the
        hit/reuse figures at scheduler cadence exactly while the cache is
        thrashing, or require a retraction that breaks monotonicity (a
        decreasing counter reads as a reset to PromQL rate()).  The
        engine commits exactly once, when the prefill actually starts."""
        self.hits += 1
        self.tokens_reused += tokens
        if source == "cross":
            self.cross_thread_hits += 1
        elif source == "host_tier":
            self.host_tier_hits += 1
        elif source == "shipped":
            self.shipped_hits += 1
        elif source == "object_tier":
            self.object_tier_hits += 1

    # -- store -----------------------------------------------------------

    def store(self, key: str, tokens: Sequence[int], pages: Sequence[int],
              shipped: bool = False, woken: bool = False) -> None:
        """Insert a finished sequence's materialized tokens along its path.

        Only whole pages are stored (`tokens` must count exactly the
        materialized KV slots — the engine drops the final sampled token,
        whose KV is never written).  Matched runs keep the cache's
        existing pages; only the unmatched suffix's pages are retained.

        ``shipped=True`` registers a run arriving via cross-replica page
        shipping (dp_router._ship_run): newly-inserted nodes carry the
        shipped marker so the thread's first lookup classifies as
        ``cache_source="shipped"``; a later normal store descending them
        (the thread's own finish on this replica) clears it.  Matched
        runs along a shipped registration are NOT re-marked — they are
        this replica's pre-existing content, and the duplicate shipped
        pages for them are simply not retained (the caller releases its
        alloc reference afterwards, freeing them).

        ``woken=True`` is the analogous marker for runs re-materialized
        from the object store (_wake_from_object): first lookups crossing
        them classify as ``cache_source="object_tier"``.  Both callers
        pass DUMMY page ids (-1) for the already-present prefix; matched
        runs never read their page entries, and the guards below make a
        dummy id inert everywhere one could otherwise be captured (fresh
        insert after a racing eviction, host-run adoption).
        """
        ps = self.pool.page_size
        n_full = min(len(pages), len(tokens) // ps)
        node = self._root
        idx = 0  # page index into the incoming sequence
        while idx < n_full:
            pkey = tuple(tokens[idx * ps:(idx + 1) * ps])
            child = node.children.get(pkey)
            if child is None:
                run_pages = list(pages[idx:n_full])
                if any(p < 0 for p in run_pages):
                    # dummy placeholder ids (delta-ship skip / object
                    # wake) whose matched node was evicted mid-operation:
                    # there is nothing real to insert here
                    break
                run_tokens = list(tokens[idx * ps:n_full * ps])
                self._retain_pages(run_pages)
                self.generation += 1
                new = _Node(run_tokens, run_pages, node)
                new.shipped = shipped
                new.woken = woken
                self._claim(new, key)
                node.children[pkey] = new
                self._n_nodes += 1
                self._n_pages += len(run_pages)
                self._leaves[new] = None
                self._leaves.pop(node, None)  # parent is no longer a leaf
                self._touch(new)
                break
            n = child.n_pages(ps)
            take = 1
            while (
                take < n
                and idx + take < n_full
                and child.tokens[take * ps:(take + 1) * ps]
                == list(tokens[(idx + take) * ps:(idx + take + 1) * ps])
            ):
                take += 1
            if take < n:
                # The run extends past this sequence's path — divergence
                # inside the run, OR our tokens ran out mid-run.  Split at
                # the boundary either way: the claim below must cover ONLY
                # the pages this thread's path actually walked, or a short
                # store would extend its ownership over another thread's
                # tail (mislabelling own/cross hits and pinning the tail
                # against invalidate()).  A host-resident run whose tier
                # payload is gone cannot split — drop the subtree and
                # retry this page index (the fresh-insert branch takes it).
                if not self._split(child, take):
                    self._remove_subtree(child)
                    continue
            if child.host_run is not None:
                # Adoption: the incoming sequence carries freshly-computed
                # pages for exactly this run's tokens — a free promotion.
                # The tier copy is dropped; the node is device-resident
                # again without any H2D traffic.  Adoption is keyed on
                # REAL page ids: a delta-ship registration or object wake
                # passes dummy (-1) entries for runs the destination
                # already holds, and adopting those would capture garbage
                # — the run stays tier-resident and promotes as usual.
                adopt = list(pages[idx:idx + take])
                if all(p >= 0 for p in adopt):
                    self._retain_pages(adopt)
                    child.pages = adopt
                    if self.tier is not None:
                        self.tier.discard(child.host_run)
                    child.host_run = None
                    self._n_pages += take
                    self._host_pages -= take
                    self._host_nodes -= 1
                    if not child.children:
                        self._leaves[child] = None
            if child.shipped and not shipped:
                # the thread's own finish stored through the shipped run:
                # it is ordinary cache content from here on
                child.shipped = False
            if child.woken and not woken and not shipped:
                # the thread's own finish stored through the woken run
                child.woken = False
            self._claim(child, key)
            self._touch(child)
            node = child
            idx += take
        self._evict_to_budget()

    def _split(self, node: _Node, take: int) -> bool:
        """Split `node` at `take` pages; the suffix becomes its child.
        Device runs move pages between the nodes (no refcount changes);
        host-resident runs split their tier payload at the same boundary.
        Returns False when the tier payload is gone — the caller must
        remove the node (its KV no longer exists anywhere)."""
        ps = self.pool.page_size
        front_run = back_run = None
        if node.host_run is not None:
            if self.tier is None:
                return False
            parts = self.tier.split(node.host_run, take)
            if parts is None:
                return False
            front_run, back_run = parts
        suffix = _Node(node.tokens[take * ps:], node.pages[take:], node)
        suffix.shipped = node.shipped  # both halves are the shipped run
        suffix.woken = node.woken
        suffix.children = node.children
        for c in suffix.children.values():
            c.parent = suffix
        suffix.keys = OrderedDict(node.keys)
        node.tokens = node.tokens[: take * ps]
        node.pages = node.pages[:take]
        node.children = {tuple(suffix.tokens[:ps]): suffix}
        if front_run is not None:
            node.host_run, suffix.host_run = front_run, back_run
            self._host_nodes += 1  # one host node became two
        self._n_nodes += 1  # pages just moved between the two nodes
        # leaf status transfers: the prefix now has a child; the suffix is
        # a leaf iff the original node was one (it inherited the children)
        # — host-resident suffixes are never pool-eviction candidates
        self._leaves.pop(node, None)
        if not suffix.children and suffix.host_run is None:
            self._leaves[suffix] = None
        return True

    # -- eviction --------------------------------------------------------

    def _remove(self, node: _Node) -> None:
        """Detach one node and release its pages (or discard its tier
        run).  No eviction counters — pressure eviction (_evict_leaf)
        counts itself; invalidate()/clear() must not read as cache thrash
        on /metrics."""
        ps = self.pool.page_size
        parent = node.parent
        if parent is not None:
            parent.children.pop(tuple(node.tokens[:ps]), None)
            if (
                parent is not self._root
                and not parent.children
                and parent.host_run is None
            ):
                self._leaves[parent] = None  # parent became a leaf
        if node.host_run is not None:
            if self.tier is not None:
                self.tier.discard(node.host_run)
            self._host_nodes -= 1
            self._host_pages -= node.n_pages(ps)
            node.host_run = None
        else:
            self._release_pages(node.pages)
            self._n_pages -= len(node.pages)
        self.generation += 1
        self._n_nodes -= 1
        self._leaves.pop(node, None)
        node.parent = None

    def _remove_subtree(self, node: _Node) -> None:
        """Remove `node` and everything below it (a lost tier run makes
        the whole subtree unreachable KV — deeper runs can never be
        attached without their prefix)."""
        stack = [node]
        order: List[_Node] = []
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        for n in reversed(order):  # children before ancestors
            self._remove(n)

    def _evict_leaf(self) -> bool:
        """Release the least-recently-used leaf — O(1) via the recency-
        ordered leaf map, not a tree walk (reclaim runs on the engine
        thread's allocation path).  Leaf-LRU by design: shared prefixes
        near the root outlive their coldest consumer.

        With a KV tier attached the victim is DEMOTED instead of dropped:
        its rows are copied device->host (async; the gather is enqueued
        before the pages are released, so in-order execution reads them
        pre-overwrite), the pool pages are freed, and the node stays in
        the tree as a host-resident run a future lookup can promote.  A
        refused/failed demote (tier full, injected fault) falls back to
        the plain drop."""
        if not self._leaves:
            return False
        self._evict_node(next(iter(self._leaves)))
        return True

    def _path_runs(self, node: _Node) -> List[List[int]]:
        """Per-node token runs of the radix path root -> `node` (the
        object tier's content-address context: a run's KV depends on its
        whole prefix).  O(path depth)."""
        runs: List[List[int]] = []
        n: Optional[_Node] = node
        while n is not None and n is not self._root:
            runs.append(list(n.tokens))
            n = n.parent
        runs.reverse()
        return runs

    def _evict_node(self, victim: _Node) -> None:
        """Demote-or-drop one leaf (the shared step of LRU eviction and
        promotion's protected reclaim)."""
        if self.tier is not None and victim.pages:
            has_obj = getattr(self.tier, "object", None) is not None
            run = self.tier.demote(
                victim.pages,
                # content-address context rides only when an object tier
                # can use it (the path walk is not free)
                path_runs=self._path_runs(victim) if has_obj else None,
                threads=list(victim.keys) if has_obj else (),
            )
            if run is not None:
                n = len(victim.pages)
                self._release_pages(victim.pages)
                self._n_pages -= n
                self._host_pages += n
                self._host_nodes += 1
                victim.pages = []
                victim.host_run = run
                # host-resident runs leave the pool-eviction LRU; the
                # tier's own second-chance LRU owns them now.  Content is
                # unchanged (still matchable), so no generation bump.
                self._leaves.pop(victim, None)
                return
        self.evictions += 1
        self.pages_evicted += len(victim.pages)
        self._remove(victim)

    def _reclaim_protected(self, pages_needed: int, protect) -> None:
        """Evict LRU leaves outside `protect` until the pool can satisfy
        `pages_needed` (promotion's reclaim).  Best-effort: released
        pages only become free when no live sequence shares them."""
        while self.pool.free_pages < pages_needed:
            victim = next(
                (nd for nd in self._leaves if nd not in protect), None
            )
            if victim is None:
                return
            self._evict_node(victim)

    def _evict_to_budget(self) -> None:
        """Enforce the page budget, PAGE-granular: the LRU leaf is trimmed
        from its tail rather than dropped whole, so a budget smaller than
        one stored run keeps the head of the shared prefix (the part every
        thread reuses) instead of zeroing the cache."""
        if self.max_pages is None:
            return
        ps = self.pool.page_size
        while self._n_pages > self.max_pages and self._leaves:
            if self.tier is not None:
                # tiered: demote the whole LRU leaf (run granularity —
                # demotion is not loss, so the partial-trim subtlety
                # below doesn't apply)
                if not self._evict_leaf():
                    break
                continue
            overage = self._n_pages - self.max_pages
            victim = next(iter(self._leaves))
            n = min(len(victim.pages), overage)
            self.pages_evicted += n
            keep = len(victim.pages) - n
            if keep <= 0:
                self.evictions += 1
                self._remove(victim)
            else:
                self._release_pages(victim.pages[keep:])
                self.generation += 1
                victim.pages = victim.pages[:keep]
                victim.tokens = victim.tokens[: keep * ps]
                self._n_pages -= n

    def reclaim(self, pages_needed: int) -> bool:
        """Evict LRU leaves until the pool can satisfy `pages_needed`.

        Released pages only become free when no live sequence shares them,
        so eviction proceeds leaf by leaf and may legitimately fail.
        """
        while self.pool.free_pages < pages_needed:
            if not self._evict_leaf():
                return False
        return True

    # -- sleep (drain-to-object, ISSUE 14) -------------------------------

    def _materialize_node(self, node: _Node):
        """Host leaves of one node's KV wherever it lives (device pages
        via a blocking D2H gather, host/disk via the tier's read-only
        peek).  None = nothing local to archive (object-resident) or a
        failed load — the sleep entry is skipped."""
        try:
            if node.pages:
                pend = self.tier.shipper.export_run(node.pages)
                return self.tier.shipper.resolve(pend)
            if node.host_run is not None:
                return self.tier.peek(node.host_run)
        except Exception:
            return None
        return None

    def _claimed_chain(self, key: str) -> List[_Node]:
        """The deepest root-anchored chain of nodes claiming `key` (the
        thread's stored path; store() claims every node it walks, so the
        claims form chains — a thread whose prompt diverged mid-history
        has several, and the deepest is its current conversation)."""
        best: List[_Node] = []
        best_tokens = 0
        stack = [
            [c] for c in self._root.children.values() if key in c.keys
        ]
        while stack:
            path = stack.pop()
            deeper = [
                c for c in path[-1].children.values() if key in c.keys
            ]
            if deeper:
                stack.extend(path + [c] for c in deeper)
                continue
            n_tok = sum(len(n.tokens) for n in path)
            if n_tok > best_tokens:
                best, best_tokens = path, n_tok
        return best

    def sleep_to_object(self) -> Dict[str, int]:
        """Flush EVERY cached run into the shared object store and write
        every claiming thread's sleep manifest — the ``POST
        /admin/drain/{replica}`` seam (autoscaler drain-then-shrink): a
        replica drained this way can be torn down without discarding any
        warm thread state, because any replica of any host sharing the
        store can wake the threads from their manifests.

        Non-destructive: archiving is a COPY (content-addressed and
        refcounted, so re-archiving present content is a reference-only
        dedupe), the tree and pool are untouched, and serving resumes
        unchanged if the replica is kept after all.  Must run with the
        scheduler quiesced (the provider parks the worker) — the D2H
        gathers read the pool the engine thread otherwise mutates."""
        if self.tier is None or getattr(self.tier, "object", None) is None:
            return {"enabled": False}
        obj = self.tier.object
        self.tier.drain(force=True)  # resolve in-flight demotes first
        ps = self.pool.page_size
        stats = {
            "enabled": True, "runs_archived": 0, "runs_failed": 0,
            "runs_skipped_store_down": 0, "manifests": 0,
            "manifests_failed": 0, "threads": 0,
        }
        bytes0 = obj.object_bytes_put
        dedupe0 = obj.dedupe_hits
        keys_seen: set = set()
        # 1) archive every run, parents before children, path accumulated
        stack = [(c, []) for c in self._root.children.values()]
        while stack:
            node, path = stack.pop()
            path_runs = path + [list(node.tokens)]
            for c in node.children.values():
                stack.append((c, path_runs))
            keys_seen.update(node.keys)
            if not obj.available():
                # store breaker open: nothing can land, so skip the D2H
                # gather + encode outright.  The drain returns a PARTIAL
                # result with honest per-run accounting — the autoscaler
                # shrinks anyway (capacity beats warm state) and the
                # skipped runs re-prefill on wake.
                stats["runs_failed"] += 1
                stats["runs_skipped_store_down"] += 1
                continue
            flat = [t for seg in path_runs for t in seg]
            if obj.has_run(obj.run_key(flat, node.n_pages(ps))):
                ok = obj.put_run(flat, None, None,
                                 node.n_pages(ps)) is not None
            else:
                payload = self._materialize_node(node)
                if payload is None and node.host_run is not None:
                    # object-resident already (archived organically)
                    ok = obj.put_run(flat, None, None,
                                     node.n_pages(ps)) is not None
                elif payload is None:
                    ok = False
                else:
                    ok = obj.put_run(flat, payload[0], payload[1],
                                     node.n_pages(ps)) is not None
            stats["runs_archived" if ok else "runs_failed"] += 1
        # 2) one manifest per claiming thread, covering its deepest chain
        for key in sorted(keys_seen):
            chain = self._claimed_chain(key)
            if not chain:
                continue
            path_runs = [list(n.tokens) for n in chain]
            tokens = [t for seg in path_runs for t in seg]
            if obj.write_manifest(key, tokens, obj.manifest_runs(path_runs)):
                stats["manifests"] += 1
            else:
                stats["manifests_failed"] += 1
        stats["threads"] = len(keys_seen)
        stats["bytes_put"] = obj.object_bytes_put - bytes0
        stats["dedupe_hits"] = obj.dedupe_hits - dedupe0
        stats["breaker_state"] = obj.breaker_state()
        return stats

    # -- agent tool-call gap (ISSUE 20) ----------------------------------

    def touch_thread(self, key: str) -> int:
        """Set the second-chance reference bit on every tier-resident run
        of `key`'s stored path (the return hint fired: the follow-up turn
        is imminent, so the thread's runs must survive host-tier LRU for
        the next few seconds).  Returns the thread's locally-resident
        token depth — the same figure thread_resident_tokens reports,
        saved a second chain walk."""
        resident = 0
        for node in self._claimed_chain(key):
            resident += len(node.tokens)
            if node.host_run is not None and self.tier is not None:
                self.tier.touch(node.host_run)
        return resident

    def thread_resident_tokens(self, key: str) -> int:
        """Tokens of `key`'s stored path resident LOCALLY — device pages
        or host/disk runs, either of which a wake serves without a store
        round trip.  The return-triggered prefetch passes this as
        ``min_depth``: object GETs only help beyond it."""
        return sum(len(n.tokens) for n in self._claimed_chain(key))

    def demote_thread(self, key: str, archive: bool = False) -> Dict[str, int]:
        """Proactively demote thread `key`'s device-resident KV down the
        tier ladder (the agent tool-call gap, ISSUE 20): the thread just
        emitted a tool call and will sit idle for the tool's runtime, so
        its pages serve nobody — free them NOW instead of waiting for
        eviction pressure to find the leaf.

        Walks the thread's deepest claimed chain leaf-ward and demotes
        each exclusively-claimed node exactly like LRU eviction's demote
        branch (node stays in the tree as a host run; content unchanged,
        no generation bump — the follow-up turn's lookup still matches
        and promotes).  Stops at the first SHARED node: claims form
        root-anchored paths, so everything above it is shared too, and a
        fan-out system prompt must stay hot for its sibling threads.  A
        refused demote (tier budget, deferral ladder) stops the walk —
        never drops: losing KV to save HBM would turn the follow-up turn
        into a re-prefill, the exact cost this path exists to avoid.

        With ``archive=True`` (KAFKA_TPU_AGENT_DEMOTE=object) the chain
        is archived into the object store FIRST and the thread's sleep
        manifest written — the same per-run protocol as
        :meth:`sleep_to_object`, scoped to one thread — so the return
        hint's wake prefetch works from ANY replica, not just this one.
        A durable archive also upgrades the refusal rule: when the host
        tier refuses a node (budget smaller than the run — the ladder's
        first rung is missing), the node drops straight to the OBJECT
        rung — removed from the tree, pages freed — because the store
        now holds the bytes and the follow-up's lookup wakes the chain
        back via the manifest.  Without a durable manifest a refusal
        still stops the walk (never trade KV for HBM blindly)."""
        stats = {"nodes": 0, "pages": 0, "dropped": 0}
        if self.tier is None:
            return stats
        chain = self._claimed_chain(key)
        has_obj = getattr(self.tier, "object", None) is not None
        durable = False
        if archive and has_obj and chain:
            # archive BEFORE demoting: _materialize_node reads device
            # pages or host runs, and a durable manifest licenses the
            # direct-to-object drop below
            stats["manifest"] = self._archive_thread_chain(key, chain)
            durable = stats["manifest"] == 1
        path_clear = True  # no on-path child left behind so far
        for node in reversed(chain):  # leaf-ward: private before shared
            if len(node.keys) > 1 or key not in node.keys:
                break  # shared prefix: stays hot for sibling threads
            if not node.pages:
                path_clear = False  # tier-resident node stays in tree
                continue
            run = self.tier.demote(
                node.pages,
                path_runs=self._path_runs(node) if has_obj else None,
                threads=list(node.keys) if has_obj else (),
            )
            if run is None:
                # tier refused.  With the chain durably archived, drop to
                # the object rung — but only a node whose children are
                # all already gone (pure-path tail): removing a fan-out
                # node would orphan live subtrees.
                if durable and path_clear and not node.children:
                    n = len(node.pages)
                    self._remove(node)
                    stats["dropped"] += 1
                    stats["pages"] += n
                    continue
                break  # keep the remainder hot, never drop
            n = len(node.pages)
            self._release_pages(node.pages)
            self._n_pages -= n
            self._host_pages += n
            self._host_nodes += 1
            node.pages = []
            node.host_run = run
            self._leaves.pop(node, None)
            path_clear = False  # node survives in the tree
            stats["nodes"] += 1
            stats["pages"] += n
        return stats

    def _archive_thread_chain(self, key: str, chain: List[_Node]) -> int:
        """Archive one thread's chain + manifest (demote_thread's object
        mode).  Returns 1 when the manifest landed, else 0."""
        obj = self.tier.object
        if not obj.available():
            return 0
        self.tier.drain(force=True)  # resolve in-flight demotes for peek
        ps = self.pool.page_size
        path: List[List[int]] = []
        for node in chain:
            path.append(list(node.tokens))
            flat = [t for seg in path for t in seg]
            if obj.has_run(obj.run_key(flat, node.n_pages(ps))):
                ok = obj.put_run(flat, None, None,
                                 node.n_pages(ps)) is not None
            else:
                payload = self._materialize_node(node)
                ok = (payload is not None
                      and obj.put_run(flat, payload[0], payload[1],
                                      node.n_pages(ps)) is not None)
            if not ok:
                # a manifest naming an absent run would truncate every
                # wake at the gap — better no manifest than a torn one
                return 0
        runs = [list(n.tokens) for n in chain]
        tokens = [t for seg in runs for t in seg]
        return 1 if obj.write_manifest(
            key, tokens, obj.manifest_runs(runs)
        ) else 0

    def invalidate(self, key: str) -> None:
        """Drop `key`'s claim; free only nodes no other thread claims.

        Shared prefix nodes (another thread's store path crosses them)
        survive, so deleting one thread never cold-starts its siblings.
        Claimed nodes form root-anchored paths (store() claims every node
        it walks), so the traversal descends only children claiming `key`
        — O(claimed path), not O(tree) — and unwinds iteratively (a long
        multi-turn thread is a deep node chain; recursion would overflow).
        Once any claim list has hit _KEYS_CAP the root-anchored invariant
        may be broken (an ancestor dropped the key while deeper nodes
        still hold it), so the sweep covers the whole tree instead —
        correctness over speed, and the tree stays page-bounded anyway.
        """
        if self._claims_capped:
            order: List[_Node] = list(self._iter_nodes())
        else:
            stack = [
                c for c in self._root.children.values() if key in c.keys
            ]
            order = []
            while stack:
                node = stack.pop()
                order.append(node)
                stack.extend(
                    c for c in node.children.values() if key in c.keys
                )
        # preorder reversed: every node is processed before its ancestors,
        # so a freed leaf can cascade up its now-empty parents
        for node in reversed(order):
            node.keys.pop(key, None)
            if not node.children and not node.keys:
                self._remove(node)

    def clear(self) -> None:
        """Release everything (not counted as pressure eviction)."""
        for node in list(self._iter_nodes()):
            if node.host_run is not None:
                if self.tier is not None:
                    self.tier.discard(node.host_run)
            else:
                self.pool.release(node.pages)
        self._root = _Node([], [], None)
        self._n_nodes = 0
        self._n_pages = 0
        self._host_nodes = 0
        self._host_pages = 0
        self._leaves = OrderedDict()
        self._page_retains = {}
        self.generation += 1
