"""Sampled per-kernel dispatch profiling (ISSUE 18, leg c).

The flight recorder's measured dispatch latency (PR 11) is derived
from fetch-maturation order — an honest *host-side* clock on device
compute, but still one hop removed from the chip: it cannot say which
kernels a dispatch spent its time in, and on CPU smoke the numbers
fold in host scheduling noise.  This module is the ground-truth
instrument under it:

* ``KAFKA_TPU_PROFILE_SAMPLE=N`` wraps every Nth ``engine.step`` in a
  ``jax.profiler`` trace written to a bounded spill directory
  (``KAFKA_TPU_PROFILE_SPILL_DIR``, default ``/tmp/kafka_tpu_kernels``;
  the last ``KAFKA_TPU_PROFILE_KEEP`` raw traces are retained for the
  Perfetto / xplane workflow, older ones pruned).  Unset or 0 = off,
  with every dispatch path byte-identical to an unprofiled build —
  the engine holds no sampler object and each hook site is one
  ``if self.kernel_sampler is not None`` branch.

* Each sample's ``*.trace.json.gz`` (the Chrome-trace JSON jax writes
  next to the xplane.pb) is parsed with stdlib gzip+json into
  per-kernel durations: events on ``/device:*`` processes when present
  (TPU/GPU), else the XLA executor worker events on CPU, host-API
  noise filtered out.  Kernels aggregate by the dispatch-kind
  composition of the sampled step (``decode``, ``prefill+decode``, …)
  into a top-K table served at ``GET /debug/kernels``.

* The sample's total device kernel time, split across the step's
  dispatch kinds in proportion to their modeled roofline seconds, is
  fed back as ``EngineMetrics.record_kernel_sample`` — the
  ``kernel_skew`` (true-device vs modeled) gauge that calibrates the
  PR 11 fetch-maturation ``model_skew`` per kind.  This is exactly the
  instrument scripts/BENCH_r06.md's TPU calibration round reads
  instead of hand math.

Trace windows are *deliberately offset*: a sample's trace starts
before step k and stops at the start of step k+1, so asynchronously
dispatched device work has the inter-step gap to land inside the
window without the sampler ever blocking the scheduler.  ``jax``
profiling is process-global (one trace at a time); the sampler and
``POST /admin/profile`` share :func:`try_acquire_trace` so they can
never collide.
"""

from __future__ import annotations

import glob
import gzip
import json
import logging
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger("kafka_tpu.kernels")

SAMPLE_ENV = "KAFKA_TPU_PROFILE_SAMPLE"
SPILL_ENV = "KAFKA_TPU_PROFILE_SPILL_DIR"
KEEP_ENV = "KAFKA_TPU_PROFILE_KEEP"

DEFAULT_SPILL_DIR = "/tmp/kafka_tpu_kernels"
DEFAULT_KEEP = 4

# host-API events that are not kernels (CPU traces put XLA worker
# events and python/runtime noise on the same host process)
_HOST_NOISE = ("ParseArguments", "ThreadpoolListener",
               "ThunkExecutor", "ExecuteHelper")


def sample_period() -> int:
    """KAFKA_TPU_PROFILE_SAMPLE: trace every Nth step (0/unset/junk =
    off).  Negative values clamp to off like every other knob."""
    raw = os.environ.get(SAMPLE_ENV)
    if raw is None or raw == "":
        return 0
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


# -- process-global trace ownership (jax allows one trace at a time) ----

_TRACE_LOCK = threading.Lock()


def try_acquire_trace() -> bool:
    """Claim the process profiler (non-blocking).  Shared with the
    on-demand POST /admin/profile capture so the two can't collide."""
    return _TRACE_LOCK.acquire(blocking=False)


def release_trace() -> None:
    try:
        _TRACE_LOCK.release()
    except RuntimeError:  # pragma: no cover - double release guard
        pass


# -- trace parsing ------------------------------------------------------


def parse_trace_dir(d: str) -> List[Tuple[str, float]]:
    """All kernel events in a profiler session dir as (name, dur_us).

    Prefers events on ``/device:*`` processes (real accelerators);
    falls back to the heuristic host filter for CPU traces.  Raises
    nothing: an unreadable trace is an empty list.
    """
    out: List[Tuple[str, float]] = []
    try:
        paths = glob.glob(os.path.join(d, "**", "*.trace.json.gz"),
                          recursive=True)
        for p in paths:
            with gzip.open(p, "rt") as f:
                data = json.load(f)
            out.extend(_parse_events(data.get("traceEvents", [])))
    except (OSError, ValueError, EOFError):
        logger.debug("unparseable trace under %s", d, exc_info=True)
    return out


def _parse_events(events: List[Dict[str, Any]]
                  ) -> List[Tuple[str, float]]:
    device_pids = set()
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and str((e.get("args") or {}).get("name", ""))
                .startswith("/device:")
                and "CPU" not in str((e.get("args") or {})["name"])):
            device_pids.add(e.get("pid"))
    out: List[Tuple[str, float]] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        name = e.get("name")
        dur = e.get("dur")
        if not name or not isinstance(dur, (int, float)) or dur <= 0:
            continue
        if device_pids:
            if e.get("pid") not in device_pids:
                continue
        elif not _looks_like_kernel(name):
            continue
        out.append((name, float(dur)))
    return out


def _looks_like_kernel(name: str) -> bool:
    """CPU-trace heuristic: XLA thunk/kernel names (``dot.4``,
    ``broadcast_add_fusion``) vs host API noise (``$profiler.py …``,
    ``PjitFunction(...)``, ``TfrtCpuExecutable::Execute``)."""
    if name.startswith("$") or "::" in name or "(" in name:
        return False
    return not any(name.startswith(p) for p in _HOST_NOISE)


# -- the sampler --------------------------------------------------------


class KernelSampler:
    """Every-Nth-step jax.profiler sampling for ONE engine.

    Engine-thread single-writer for the sampling state; the aggregated
    kernel table is read by ``/debug/kernels`` under ``_agg_lock``.
    """

    def __init__(self, period: int,
                 spill_dir: Optional[str] = None,
                 keep: Optional[int] = None):
        if period <= 0:
            raise ValueError("KernelSampler period must be > 0 "
                             "(0 = off means: do not construct one)")
        self.period = period
        self.spill_dir = spill_dir or os.environ.get(
            SPILL_ENV) or DEFAULT_SPILL_DIR
        try:
            keep = int(os.environ.get(KEEP_ENV, "")) if keep is None \
                else keep
        except ValueError:
            keep = DEFAULT_KEEP
        self.keep = max(1, keep)
        self._step_i = 0
        self._open_dir: Optional[str] = None
        self._open_modeled: Dict[str, float] = {}
        self._sample_seq = 0
        self.samples_total = 0
        self.sample_failures = 0
        self.last_sample_t: Optional[float] = None
        self._agg_lock = threading.Lock()
        # (kind_label, kernel) -> [count, total_us]
        self._kernels: Dict[Tuple[str, str], List[float]] = {}
        # kind_label -> total device us across samples
        self._kind_us: Dict[str, float] = {}

    # -- engine hooks (engine thread) -----------------------------------

    def on_step_begin(self, metrics: Any) -> None:
        """Called at the top of engine.step: closes the previous
        sample's window (async device work has had the inter-step gap
        to land), then opens a new one when the step is due."""
        if self._open_dir is not None:
            self._finish_sample(metrics)
        due = self._step_i % self.period == 0
        self._step_i += 1
        if due:
            self._start_sample(metrics)

    def close(self, metrics: Any = None) -> None:
        """Stop any open window (engine shutdown / test teardown)."""
        if self._open_dir is not None:
            self._finish_sample(metrics)

    # -- sampling internals ---------------------------------------------

    def _modeled_by_kind(self, metrics: Any) -> Dict[str, float]:
        try:
            return {k: u.modeled_s for k, u in metrics.util.items()}
        except Exception:
            return {}

    def _start_sample(self, metrics: Any) -> None:
        if not try_acquire_trace():
            return  # an on-demand capture owns the profiler
        d = os.path.join(self.spill_dir,
                         f"sample_{self._sample_seq:06d}")
        self._sample_seq += 1
        try:
            import jax
            os.makedirs(d, exist_ok=True)
            jax.profiler.start_trace(d)
        except Exception:
            self.sample_failures += 1
            release_trace()
            logger.debug("profiler start_trace failed", exc_info=True)
            return
        self._open_dir = d
        self._open_modeled = self._modeled_by_kind(metrics)

    def _finish_sample(self, metrics: Any) -> None:
        d = self._open_dir
        self._open_dir = None
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            self.sample_failures += 1
            logger.debug("profiler stop_trace failed", exc_info=True)
            release_trace()
            return
        release_trace()
        # the sampled step's dispatch-kind composition, read off the
        # same per-kind modeled-seconds deltas the calibration uses
        # (record_measured_dispatch accrues modeled_s whether or not
        # the flight recorder is on)
        deltas: Dict[str, float] = {}
        if metrics is not None:
            after = self._modeled_by_kind(metrics)
            deltas = {
                k: after.get(k, 0.0) - self._open_modeled.get(k, 0.0)
                for k in after
            }
            deltas = {k: v for k, v in deltas.items() if v > 0}
        kinds = "+".join(sorted(deltas)) or "idle"
        kernels = parse_trace_dir(d)
        total_us = sum(dur for _, dur in kernels)
        with self._agg_lock:
            self.samples_total += 1
            self.last_sample_t = time.time()
            for name, dur in kernels:
                slot = self._kernels.setdefault((kinds, name), [0, 0.0])
                slot[0] += 1
                slot[1] += dur
            if total_us > 0:
                self._kind_us[kinds] = self._kind_us.get(
                    kinds, 0.0) + total_us
        # calibration feedback: split the sample's device time across
        # the step's kinds in proportion to their modeled seconds
        modeled_total = sum(deltas.values())
        if metrics is not None and total_us > 0 and modeled_total > 0:
            try:
                for k, v in deltas.items():
                    share = v / modeled_total
                    metrics.record_kernel_sample(
                        k, total_us * 1e-6 * share, v)
            except Exception:  # pragma: no cover - defensive
                logger.debug("kernel calibration failed", exc_info=True)
        self._prune_spill()

    def _prune_spill(self) -> None:
        """Keep the newest ``keep`` raw sample dirs (Perfetto/xplane
        workflow); parsing is done, older raw traces are dead weight."""
        try:
            dirs = sorted(glob.glob(
                os.path.join(self.spill_dir, "sample_*")))
            for d in dirs[: max(0, len(dirs) - self.keep)]:
                shutil.rmtree(d, ignore_errors=True)
        except OSError:  # pragma: no cover - best effort
            pass

    # -- export ----------------------------------------------------------

    def table(self, top_k: int = 20) -> List[Dict[str, Any]]:
        """Top-K kernels by total device time, across all samples."""
        with self._agg_lock:
            rows = [
                {
                    "kind": kinds,
                    "kernel": name,
                    "count": int(c),
                    "total_us": round(us, 3),
                    "avg_us": round(us / c, 3) if c else 0.0,
                    "frac": round(
                        us / self._kind_us[kinds], 4)
                    if self._kind_us.get(kinds) else 0.0,
                }
                for (kinds, name), (c, us) in self._kernels.items()
            ]
        rows.sort(key=lambda r: -r["total_us"])
        return rows[: max(1, top_k)]

    def snapshot(self, top_k: int = 20) -> Dict[str, Any]:
        """GET /debug/kernels payload."""
        with self._agg_lock:
            kind_us = {k: round(v, 3)
                       for k, v in self._kind_us.items()}
        return {
            "period": self.period,
            "spill_dir": self.spill_dir,
            "keep": self.keep,
            "samples_total": self.samples_total,
            "sample_failures": self.sample_failures,
            "last_sample_t": self.last_sample_t,
            "device_us_by_kind": kind_us,
            "kernels": self.table(top_k),
        }


def build_from_env() -> Optional[KernelSampler]:
    """One sampler per engine when KAFKA_TPU_PROFILE_SAMPLE > 0, else
    None (the byte-identical off state)."""
    period = sample_period()
    if period <= 0:
        return None
    return KernelSampler(period)
