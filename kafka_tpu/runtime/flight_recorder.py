"""Scheduler flight recorder: the measured dispatch timeline (ISSUE 11).

The telemetry plane (ISSUE 10) tells the autoscaler *how the replica is
doing* — attainment, goodput, modeled MFU.  It cannot say *what the
scheduler decided* on any given iteration, and when a replica dies
mid-burst nothing survives to explain the last seconds.  This module is
the black box under both gaps:

* **Ring** — a fixed-size, allocation-free ring of per-scheduler-
  iteration records (`KAFKA_TPU_FLIGHT_RING` steps; 0 = off, with every
  engine dispatch path byte-identical to a recorder-less build — each
  hook site is one ``if engine.flight is not None`` branch, the same
  discipline as tracing).  One record = one `engine.step()`: wall
  timestamps, which dispatch kinds ran (prefill / decode / fused /
  verify / host-constrained groups), batch composition (lanes, token
  counts, speculative candidates, chained/awaited constrained lanes),
  admission/preempt/park/degrade cause-code counts, queue/page/tier
  pressure gauges, and the iteration's modeled flop/byte cost next to
  the MEASURED dispatch latency derived from fetch-maturation timing.
  Records are plain ``__slots__`` objects overwritten in place; nothing
  on the hot path allocates beyond the one integer-field stores.

* **Measured dispatch latency** — the async fetch pipeline already
  observes when each dispatch's compute completes (`_Fetch.t_ready`,
  polled by ``engine._stamp_ready``).  The gap from ``max(dispatch
  enqueue, previous completion)`` to this completion is the device time
  the dispatch actually took (in-order execution: a queued dispatch
  starts when its predecessor finishes).  Summed per dispatch kind
  against the planner's modeled roofline time it yields the
  modeled-vs-measured skew gauge (``kafka_tpu_dispatch_model_skew``)
  that calibrates the PR 10 MFU/HBM-BW estimates.  Completion times are
  polled at scheduler cadence, so individual samples are quantized to
  one iteration — the per-kind SUMS are the calibrated quantity, and
  consecutive completions observed by one poll telescope into the first
  sample, keeping the sums honest.

* **Anomaly detectors** — step-cadence checks over the staged record
  (throttled, never allocating): queue stall (requests waiting, no
  dispatch completed for ``KAFKA_TPU_ANOMALY_STALL_S``), fetch-pipeline
  starvation (the oldest in-flight fetch stuck past the stall bound),
  MFU collapse (1m decode MFU under ``KAFKA_TPU_ANOMALY_MFU_FRAC`` of
  the since-boot figure while still decoding), and prefill convoy
  (prefill dispatches monopolizing the engine past
  ``KAFKA_TPU_ANOMALY_CONVOY_S`` while decode work is backlogged).
  Each firing is edge-triggered: one counter increment
  (``EngineMetrics.anomaly_*`` -> ``kafka_tpu_anomalies_total``), one
  log line, one tracing instant event on the active requests' traces,
  and an entry in the ``anomalies`` section of ``/admin/signals`` while
  the condition holds — the autoscaler's "something is wrong, don't
  scale on stale math" input.

* **Postmortem capture** — on engine failure (``recover_from_failure``),
  replica quarantine (``dp_router._note_failure``), or a recovery that
  itself dies (``worker._fail_all``), the ring plus a full metrics
  snapshot and the active-lane table is dumped as one JSON file next to
  the persisted trace rings (``KAFKA_TPU_FLIGHT_DIR``, defaulting to
  ``KAFKA_TPU_TRACE_PERSIST_DIR``), with file names sanitized exactly
  like the persisted traces.  ``GET /debug/flight/{replica}`` serves
  the live ring; ``scripts/flightview.py`` pretty-prints both.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger("kafka_tpu.flight")

RING_ENV = "KAFKA_TPU_FLIGHT_RING"
DIR_ENV = "KAFKA_TPU_FLIGHT_DIR"
STALL_ENV = "KAFKA_TPU_ANOMALY_STALL_S"
CONVOY_ENV = "KAFKA_TPU_ANOMALY_CONVOY_S"
MFU_FRAC_ENV = "KAFKA_TPU_ANOMALY_MFU_FRAC"

# postmortem files kept per directory (oldest pruned at write time)
POSTMORTEM_KEEP = 32
POSTMORTEM_VERSION = 1

# Dispatch-kind bits for one scheduler iteration's record.  An iteration
# can set several (e.g. a prefill chunk + the decode batch).
KIND_PREFILL = 1
KIND_DECODE = 2
KIND_MULTI = 4      # fused multi-step decode
KIND_VERIFY = 8     # speculative verify
KIND_MIXED = 16     # host-constrained chained/awaited groups
KIND_NAMES = (
    (KIND_PREFILL, "prefill"),
    (KIND_DECODE, "decode"),
    (KIND_MULTI, "multi"),
    (KIND_VERIFY, "verify"),
    (KIND_MIXED, "mixed"),
)

# Scheduler cause codes: WHY the scheduler touched a request this
# iteration.  The README "Flight recorder" section is the user-facing
# table; flightview.py renders these names.
CAUSES = (
    "admit",          # waiting head seated into a decode slot (prefill)
    "admit_parked",   # parked lane seated into a freed decode slot
    "park",           # off-slot prefill started (oversubscription)
    "page_blocked",   # waiting head blocked on KV pages this iteration
    "preempt",        # a lane rolled back to waiting (page pressure)
    "park_rollback",  # a parked lane rolled back to the waiting queue
    "degrade",        # grammar lane degraded to the host mask path
    "overtight",      # over-tight constrained mask row
    "timeout",        # request deadline expired (finish_reason=timeout)
    "reject",         # admission rejection (waiting queue full, 429)
    # agent-native scheduling (ISSUE 20)
    "agent_demote",   # tool-gap linger expired: thread KV demoted
    "bg_admit",       # background-class request admitted (idle capacity)
    "bg_prefill",     # background lane advanced one prefill chunk
    "bg_yield",       # background prefill yielded to interactive work
)
CAUSE_INDEX = {name: i for i, name in enumerate(CAUSES)}

ANOMALY_KINDS = (
    "queue_stall",
    "fetch_starvation",
    "mfu_collapse",
    "prefill_convoy",
    # device-truth detectors (ISSUE 18): the compile observatory's
    # level-held storm condition (XLA recompiling under live traffic —
    # the autoscaler refuses to resize while it holds) and measured
    # HBM headroom under the watermark (runtime/planner.MemoryMonitor)
    "compile_storm",
    "hbm_pressure",
)


def ring_default() -> int:
    """KAFKA_TPU_FLIGHT_RING with nonsense clamped to the default (256
    records ~= a few seconds of busy scheduling, a few minutes idle)."""
    raw = os.environ.get(RING_ENV)
    if raw is None or raw == "":
        return 256
    try:
        return max(0, int(raw))
    except ValueError:
        return 256


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def postmortem_dir() -> Optional[str]:
    """Where postmortem dumps land: KAFKA_TPU_FLIGHT_DIR when set
    (explicit "" disables), else alongside the persisted trace rings
    (tracing.persist_dir(), itself defaulting to
    KAFKA_TPU_TRACE_PERSIST_DIR / <disk tier>/traces).  None = no dump
    (logged once per dump attempt at debug level)."""
    env = os.environ
    if DIR_ENV in env:
        return env[DIR_ENV] or None
    try:
        from .. import tracing as _tracing

        d = _tracing.persist_dir()
        if d:
            return d
    except Exception:  # pragma: no cover - tracing import cycles
        pass
    d = env.get("KAFKA_TPU_TRACE_PERSIST_DIR")
    if d:
        return d
    disk = env.get("KAFKA_TPU_KV_DISK_TIER_DIR")
    if disk:
        return os.path.join(disk, "traces")
    return None


def sanitize_name(raw: str) -> str:
    """Filesystem-safe file-name stem — the SAME derivation as the
    persisted traces (one shared helper, tracing.sanitize_stem), so
    hostile content (a reason string built from an exception message,
    say) can never traverse out of the dump directory and a hardening
    change to the rule cannot drift between the two artifact kinds."""
    from ..tracing import sanitize_stem

    return sanitize_stem(raw)


class _Rec:
    """One scheduler iteration, overwritten in place (ring slot)."""

    __slots__ = (
        "seq", "t", "gap_ms",
        "kinds", "lanes", "toks", "steps",
        "prefill_lanes", "prefill_toks",
        "spec_cands", "chained", "awaited",
        "queue_depth", "active", "parked", "pending", "pending_steps",
        "pages_free", "pages_total", "cache_pages", "tier_bytes",
        "flops", "hbm_bytes", "modeled_ms", "measured_ms",
        "emitted", "causes",
    )

    def __init__(self, n_causes: int):
        self.causes = [0] * n_causes
        self.reset()

    def reset(self) -> None:
        self.seq = -1
        self.t = 0.0
        self.gap_ms = 0.0
        self.kinds = 0
        self.lanes = 0
        self.toks = 0
        self.steps = 0
        self.prefill_lanes = 0
        self.prefill_toks = 0
        self.spec_cands = 0
        self.chained = 0
        self.awaited = 0
        self.queue_depth = 0
        self.active = 0
        self.parked = 0
        self.pending = 0
        self.pending_steps = 0
        self.pages_free = 0
        self.pages_total = 0
        self.cache_pages = 0
        self.tier_bytes = 0
        self.flops = 0.0
        self.hbm_bytes = 0.0
        self.modeled_ms = 0.0
        self.measured_ms = 0.0
        self.emitted = 0
        for i in range(len(self.causes)):
            self.causes[i] = 0

    def to_dict(self, wall_off: float) -> Dict[str, Any]:
        kinds = [name for bit, name in KIND_NAMES if self.kinds & bit]
        causes = {
            CAUSES[i]: n for i, n in enumerate(self.causes) if n
        }
        return {
            "seq": self.seq,
            "t": round(self.t + wall_off, 4),
            "gap_ms": round(self.gap_ms, 3),
            "kinds": kinds,
            "lanes": self.lanes,
            "toks": self.toks,
            "steps": self.steps,
            "prefill_lanes": self.prefill_lanes,
            "prefill_toks": self.prefill_toks,
            "spec_cands": self.spec_cands,
            "chained": self.chained,
            "awaited": self.awaited,
            "queue_depth": self.queue_depth,
            "active": self.active,
            "parked": self.parked,
            "pending": self.pending,
            "pending_steps": self.pending_steps,
            "pages_free": self.pages_free,
            "pages_total": self.pages_total,
            "cache_pages": self.cache_pages,
            "tier_bytes": self.tier_bytes,
            "flops": round(self.flops, 0),
            "hbm_bytes": round(self.hbm_bytes, 0),
            "modeled_ms": round(self.modeled_ms, 4),
            "measured_ms": round(self.measured_ms, 4),
            "emitted": self.emitted,
            "causes": causes,
        }


class FlightRecorder:
    """Per-engine scheduler flight recorder (engine-thread single-writer).

    The engine stages one iteration's facts through the ``note_*`` calls
    and commits them with ``finish_step(engine)`` at the end of
    ``step()``.  Reads from other threads (``/debug/flight``,
    ``/admin/signals``) are torn-tolerant exactly like the metrics
    snapshot: a record being overwritten may read mixed, one iteration
    stale at worst.
    """

    def __init__(self, size: int, replica: Optional[int] = None):
        if size <= 0:
            raise ValueError("FlightRecorder size must be > 0 (0 = off "
                             "means: do not construct one)")
        self.size = size
        self.replica = replica
        self._ring: List[_Rec] = [_Rec(len(CAUSES)) for _ in range(size)]
        self.next_seq = 0  # total records appended (monotone)
        self.postmortems = 0
        # monotonic->wall offset so exported timestamps correlate with
        # trace spans and log lines (computed once; drift is irrelevant
        # at flight-recorder resolution)
        self._wall_off = time.time() - time.monotonic()
        # staging for the in-progress iteration
        self._stage = _Rec(len(CAUSES))
        self._last_finish_t: Optional[float] = None
        # detector state
        self.stall_s = max(0.05, _env_float(STALL_ENV, 5.0))
        self.convoy_s = max(0.05, _env_float(CONVOY_ENV, self.stall_s))
        self.mfu_collapse_frac = min(
            1.0, max(0.0, _env_float(MFU_FRAC_ENV, 0.25))
        )
        self._last_dispatch_t: Optional[float] = None
        self._last_pop_t: Optional[float] = None
        self._convoy_since: Optional[float] = None
        self._mfu_check_t = 0.0
        # Gate-level 429s arrive on the EVENT LOOP thread (the serving
        # gate catches nearly everything under sustained overload — the
        # engine backstop sees only the race leftovers), while the stage
        # is engine-thread single-writer.  They land here via
        # note_gate_reject (GIL-atomic-enough increment, the same
        # tolerance record_rejection uses) and drain into the next
        # committed record's "reject" cause — without this the ring of
        # an overload burst would read as if almost nothing was shed.
        self.gate_rejects = 0
        # kind -> {"active": bool, "since": wall_s, "detail": str}
        self.anomaly_state: Dict[str, Dict[str, Any]] = {
            k: {"active": False, "since": None, "detail": None}
            for k in ANOMALY_KINDS
        }

    # -- per-iteration staging (engine thread) ---------------------------

    def note_dispatch(self, kind: int, lanes: int, toks: int,
                      steps: int = 1) -> None:
        s = self._stage
        s.kinds |= kind
        s.lanes += lanes
        s.toks += toks
        s.steps += steps

    def note_prefill(self, lanes: int, toks: int) -> None:
        s = self._stage
        s.kinds |= KIND_PREFILL
        s.prefill_lanes += lanes
        s.prefill_toks += toks

    def note_spec(self, candidates: int) -> None:
        self._stage.spec_cands += candidates

    def note_constrained(self, chained: int, awaited: int) -> None:
        s = self._stage
        if chained or awaited:
            s.kinds |= KIND_MIXED
        s.chained += chained
        s.awaited += awaited

    def note_cause(self, name: str, n: int = 1) -> None:
        self._stage.causes[CAUSE_INDEX[name]] += n

    def note_gate_reject(self) -> None:
        """A gate-level HTTP 429 (event-loop thread; see gate_rejects).
        Safe cross-thread: one int increment, drained by finish_step."""
        self.gate_rejects += 1

    def note_cost(self, flops: float, hbm_bytes: float,
                  modeled_s: Optional[float]) -> None:
        s = self._stage
        s.flops += flops
        s.hbm_bytes += hbm_bytes
        if modeled_s is not None:
            s.modeled_ms += modeled_s * 1e3

    def note_measured(self, measured_s: float) -> None:
        self._stage.measured_ms += measured_s * 1e3

    def note_pop(self, emitted: int) -> None:
        """A fetch entry matured and was processed (host side)."""
        self._last_pop_t = time.monotonic()
        self._stage.emitted += emitted

    # -- commit + detectors ---------------------------------------------

    def finish_step(self, engine: Any,
                    now: Optional[float] = None) -> None:
        """Commit the staged iteration into the ring and run the anomaly
        detectors.  `engine` is read for the pressure gauges (duck-typed;
        every read is defensive so a failing engine can still commit its
        final partial record from the postmortem path)."""
        now = time.monotonic() if now is None else now
        s = self._stage
        # drain gate-level 429s banked by the event-loop thread into
        # this record's reject cause (subtract what we took — increments
        # landing mid-drain survive for the next record)
        taken = self.gate_rejects
        if taken:
            self.gate_rejects -= taken
            s.causes[CAUSE_INDEX["reject"]] += taken
        s.seq = self.next_seq
        s.t = now
        if self._last_finish_t is not None:
            s.gap_ms = (now - self._last_finish_t) * 1e3
        self._last_finish_t = now
        # pressure gauges straight off the engine (single thread)
        try:
            s.queue_depth = len(engine.waiting)
            s.parked = len(engine.parked)
            s.active = engine.num_active
            s.pending = len(engine._pending)
            s.pending_steps = engine._pending_steps
            pool = engine.pool
            s.pages_free = pool.free_pages
            s.pages_total = pool.num_pages
            pc = engine.prefix_cache
            s.cache_pages = pc.total_pages if pc is not None else 0
            tier = getattr(engine, "kv_tier", None)
            s.tier_bytes = tier.host_bytes if tier is not None else 0
        except Exception:  # pragma: no cover - partial postmortem commit
            pass
        self._detect(engine, s, now)
        # commit: overwrite the ring slot in place (no allocation)
        rec = self._ring[self.next_seq % self.size]
        rec.seq = s.seq
        rec.t = s.t
        rec.gap_ms = s.gap_ms
        rec.kinds = s.kinds
        rec.lanes = s.lanes
        rec.toks = s.toks
        rec.steps = s.steps
        rec.prefill_lanes = s.prefill_lanes
        rec.prefill_toks = s.prefill_toks
        rec.spec_cands = s.spec_cands
        rec.chained = s.chained
        rec.awaited = s.awaited
        rec.queue_depth = s.queue_depth
        rec.active = s.active
        rec.parked = s.parked
        rec.pending = s.pending
        rec.pending_steps = s.pending_steps
        rec.pages_free = s.pages_free
        rec.pages_total = s.pages_total
        rec.cache_pages = s.cache_pages
        rec.tier_bytes = s.tier_bytes
        rec.flops = s.flops
        rec.hbm_bytes = s.hbm_bytes
        rec.modeled_ms = s.modeled_ms
        rec.measured_ms = s.measured_ms
        rec.emitted = s.emitted
        for i, n in enumerate(s.causes):
            rec.causes[i] = n
        self.next_seq += 1
        s.reset()

    def _detect(self, engine: Any, s: _Rec, now: float) -> None:
        metrics = getattr(engine, "metrics", None)
        dispatched = s.kinds != 0
        # queue stall: requests are waiting and no dispatch has COMPLETED
        # for stall_s — the autoscaler must not scale on a wedged
        # replica's stale utilization math.  Armed only once a dispatch
        # has been seen (cold start / idle wake is admission latency, not
        # a stall).
        stalled = (
            s.queue_depth > 0
            and self._last_dispatch_t is not None
            and now - self._last_dispatch_t > self.stall_s
        )
        if stalled:
            # fire even when THIS iteration finally dispatched: the queue
            # sat undisipatched past the bound, which is the event (a
            # delayed step that then proceeds still stalled its clients).
            # The anomaly stays ACTIVE across consecutive stalled
            # iterations — a chronic slow-cadence stall (every step
            # slower than the bound) is ONE episode: one counter edge,
            # continuously visible in /admin/signals, rather than a
            # fire+clear per iteration that the autoscaler's poll would
            # never observe.
            self._fire(
                engine, metrics, "queue_stall", now,
                f"depth={s.queue_depth} no dispatch for "
                f"{now - self._last_dispatch_t:.2f}s",
            )
        else:
            self._clear("queue_stall")  # cadence recovered / queue empty
        if dispatched:
            self._last_dispatch_t = now
        elif not (s.active or s.queue_depth or s.parked or s.pending):
            self._last_dispatch_t = None  # idle: re-arm on next one
        # fetch-pipeline starvation: the OLDEST in-flight fetch has been
        # stuck past the stall bound.  The drain rules force-pop aged
        # entries within fetch_wait_s normally; an entry this old means
        # the device never finished its compute (is_ready stayed false).
        head_t0 = None
        try:
            pending = engine._pending
            if pending:
                head_t0 = pending[0].t0
        except Exception:
            pending = None
        if head_t0 is not None and now - head_t0 > self.stall_s:
            self._fire(
                engine, metrics, "fetch_starvation", now,
                f"oldest fetch in flight {now - head_t0:.2f}s",
            )
        else:
            self._clear("fetch_starvation")
        # prefill convoy: prefill dispatches monopolize the engine while
        # OTHER work is backlogged — the pattern that melts TPOT p99
        # under a long-prompt storm.  The backlog must be work beyond the
        # prefilling lanes themselves (waiting queue): s.active counts
        # seated PREFILLING lanes too, so gating on it would flag every
        # single long prompt's normal chunked warm-up as an anomaly and
        # hold the autoscaler exactly when scale-up might help.
        convoy = (
            s.kinds & KIND_PREFILL
            and not s.kinds & (KIND_DECODE | KIND_MULTI | KIND_VERIFY)
            and s.queue_depth > 0
        )
        if convoy:
            if self._convoy_since is None:
                self._convoy_since = now
            elif now - self._convoy_since > self.convoy_s:
                self._fire(
                    engine, metrics, "prefill_convoy", now,
                    f"prefill-only for {now - self._convoy_since:.2f}s "
                    f"(queue={s.queue_depth} active={s.active})",
                )
        else:
            self._convoy_since = None
            self._clear("prefill_convoy")
        # MFU collapse (throttled to ~1 Hz): the last minute's decode MFU
        # fell under mfu_collapse_frac of the since-boot figure while the
        # engine is still decoding — the modeled numbers went stale.
        if metrics is not None and now - self._mfu_check_t >= 1.0:
            self._mfu_check_t = now
            try:
                self._check_mfu(engine, metrics, now)
            except Exception:  # pragma: no cover - defensive
                pass
        # compile storm (ISSUE 18): the process compile observatory is
        # level-holding the condition; this detector edge-counts it per
        # replica and keeps it in the active set the autoscaler reads.
        try:
            from . import compile_log

            obs = compile_log.get()
            if obs is not None:
                if obs.storm_active():
                    self._fire(
                        engine, metrics, "compile_storm", now,
                        f"{obs.storm_n}+ compiles in {obs.storm_s:.0f}s "
                        "under live traffic",
                    )
                else:
                    self._clear("compile_storm")
        except Exception:  # pragma: no cover - defensive
            pass
        # HBM pressure (ISSUE 18): MEASURED device headroom dropped
        # under the watermark — the resident set outgrew the plan
        # (plan_skew tells by how much); the degradation ladder input.
        try:
            mm = getattr(engine, "memory_monitor", None)
            if mm is not None:
                if mm.pressure():
                    sec = mm.section() or {}
                    self._fire(
                        engine, metrics, "hbm_pressure", now,
                        f"headroom "
                        f"{sec.get('hbm_headroom_bytes', 0) / 2**20:.0f}"
                        f"MiB (skew {sec.get('hbm_plan_skew', 0.0)})",
                    )
                else:
                    self._clear("hbm_pressure")
        except Exception:  # pragma: no cover - defensive
            pass

    def _check_mfu(self, engine: Any, metrics: Any, now: float) -> None:
        peak = metrics.peak_flops
        u = metrics.util.get("decode") if metrics.util else None
        if not peak or u is None or u.busy_s < 5.0:
            return
        w = metrics._util_window["decode"].sums(60.0, now=now)
        if w[2] < 1.0:
            self._clear("mfu_collapse")
            return  # not decoding this minute: idle, not collapsed
        mfu_total = u.flops / (u.busy_s * peak)
        mfu_1m = w[0] / (w[2] * peak)
        if mfu_total > 0 and mfu_1m < self.mfu_collapse_frac * mfu_total:
            self._fire(
                engine, metrics, "mfu_collapse", now,
                f"decode mfu_1m={mfu_1m:.4f} vs total={mfu_total:.4f}",
            )
        else:
            self._clear("mfu_collapse")

    def _fire(self, engine: Any, metrics: Any, kind: str, now: float,
              detail: str) -> None:
        st = self.anomaly_state[kind]
        st["detail"] = detail
        if st["active"]:
            return  # level holds; edge already counted
        st["active"] = True
        st["since"] = now + self._wall_off
        if metrics is not None:
            setattr(metrics, f"anomaly_{kind}",
                    getattr(metrics, f"anomaly_{kind}") + 1)
        logger.warning(
            "flight anomaly %s%s: %s", kind,
            f" (replica {self.replica})" if self.replica is not None
            else "", detail,
        )
        # punctuate the active requests' timelines (traced only; bounded)
        try:
            from .tracing import add_event

            n = 0
            for req in engine._requests.values():
                if getattr(req, "trace", None) is not None:
                    add_event(req.trace, "anomaly",
                              {"kind": kind, "detail": detail})
                    n += 1
                    if n >= 8:
                        break
        except Exception:  # pragma: no cover - defensive
            pass

    def _clear(self, kind: str) -> None:
        st = self.anomaly_state[kind]
        if st["active"]:
            st["active"] = False
            st["since"] = None
            st["detail"] = None

    # -- export ----------------------------------------------------------

    def active_anomalies(self) -> List[Dict[str, Any]]:
        out = []
        for kind in ANOMALY_KINDS:
            st = self.anomaly_state[kind]
            if st["active"]:
                out.append({
                    "kind": kind,
                    "since": st["since"],
                    "detail": st["detail"],
                })
        return out

    def records(self) -> List[Dict[str, Any]]:
        """Ring contents oldest -> newest (torn-tolerant copy)."""
        out = []
        hi = self.next_seq
        lo = max(0, hi - self.size)
        for seq in range(lo, hi):
            rec = self._ring[seq % self.size]
            if rec.seq == seq:  # skip slots mid-overwrite / never written
                out.append(rec.to_dict(self._wall_off))
        return out

    def snapshot(self) -> Dict[str, Any]:
        return {
            "ring_size": self.size,
            "next_seq": self.next_seq,
            "replica": self.replica,
            "causes": list(CAUSES),
            "anomalies": {
                "active": self.active_anomalies(),
            },
            "records": self.records(),
        }

    # -- postmortem ------------------------------------------------------

    def dump_postmortem(
        self,
        reason: str,
        lanes: Optional[List[Dict[str, Any]]] = None,
        metrics_snapshot: Optional[Dict[str, Any]] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Write the ring + context as one postmortem JSON file.

        Best-effort and exception-free: this runs on failure paths where
        a second exception would mask the first.  Returns the path (None
        when no dump directory is configured or the write failed)."""
        d = postmortem_dir()
        if d is None:
            logger.debug("no postmortem dir configured; skipping %s dump",
                         reason)
            return None
        payload = {
            "version": POSTMORTEM_VERSION,
            "kind": "flight_postmortem",
            "reason": reason,
            "replica": self.replica,
            "pid": os.getpid(),
            "t_wall": time.time(),
            "ring_size": self.size,
            "next_seq": self.next_seq,
            "causes": list(CAUSES),
            "anomalies": {
                kind: dict(self.anomaly_state[kind])
                for kind in ANOMALY_KINDS
            },
            "records": self.records(),
            "lanes": lanes or [],
            "metrics": metrics_snapshot or {},
        }
        if extra:
            payload.update(extra)
        stem = sanitize_name(
            f"{reason}-r{self.replica if self.replica is not None else 0}"
            f"-{self.next_seq}-{os.getpid()}"
        )
        path = os.path.join(d, f"postmortem.{stem}.flight.json")
        try:
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except (OSError, TypeError, ValueError) as e:
            logger.warning("postmortem dump failed (%s): %s", reason, e)
            return None
        self.postmortems += 1
        _prune_postmortems(d)
        logger.error("flight postmortem (%s) written to %s", reason, path)
        return path


def _prune_postmortems(d: str) -> None:
    """Bound the postmortem set (oldest dropped)."""
    try:
        names = [n for n in os.listdir(d) if n.endswith(".flight.json")]
        if len(names) <= POSTMORTEM_KEEP:
            return
        paths = [os.path.join(d, n) for n in names]
        paths.sort(key=lambda p: os.path.getmtime(p))
        for p in paths[: len(paths) - POSTMORTEM_KEEP]:
            os.unlink(p)
    except OSError:  # pragma: no cover - best effort
        pass


def list_postmortems(d: Optional[str] = None) -> List[str]:
    """Postmortem files in the dump dir, newest first (flightview)."""
    d = d if d is not None else postmortem_dir()
    if not d:
        return []
    try:
        paths = [os.path.join(d, n) for n in os.listdir(d)
                 if n.endswith(".flight.json")]
        paths.sort(key=lambda p: os.path.getmtime(p), reverse=True)
        return paths
    except OSError:
        return []
