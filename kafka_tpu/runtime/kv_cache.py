"""Paged KV cache: device-side page pool + host-side allocator.

TPU-first replacement for what the reference outsourced entirely (its KV
state lived inside remote providers).  Here the KV pool is two device arrays
[L, num_pages * page_size, Hkv*D] (heads merged into the minor axis — the
lane-tile alignment the Pallas paged kernel's DMAs require; see
make_kv_pool_arrays); sequences own ordered lists of physical pages.  The
host-side allocator is refcounted so pages can be shared between sequences —
the mechanism behind thread-keyed cache reuse and prefix sharing (BASELINE
configs 2 and 5).

Page tables, not the pool, are what the jitted step functions consume: a
[B, max_pages] int32 array per step, from which read/write flat indices are
derived *on device* (models/llama.py PagedView).  Physical page 0 is
reserved as the trash page — inactive batch slots point their writes at it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.config import ModelConfig
from .failpoints import failpoint

TRASH_PAGE = 0


class OutOfPagesError(RuntimeError):
    """Raised when the pool cannot satisfy an allocation; the scheduler
    reacts by preempting or queueing (never a user-facing crash)."""


@dataclasses.dataclass
class SequencePages:
    """Host-side record of the pages backing one sequence."""

    seq_id: str
    pages: List[int] = dataclasses.field(default_factory=list)
    length: int = 0  # tokens currently materialized in the cache

    def capacity(self, page_size: int) -> int:
        return len(self.pages) * page_size


class PagePool:
    """Refcounted allocator over the physical page axis.

    Device arrays are owned by the engine (they thread through jit); this
    class only tracks ownership/refcounts on host.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is the trash page)")
        self.num_pages = num_pages
        self.page_size = page_size
        self.refcount = np.zeros(num_pages, dtype=np.int32)
        self.refcount[TRASH_PAGE] = 1  # never allocated
        self._free: List[int] = list(range(num_pages - 1, 0, -1))  # stack

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int = 1) -> List[int]:
        failpoint("kv.alloc")
        if n > len(self._free):
            raise OutOfPagesError(f"need {n} pages, have {len(self._free)} free")
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self.refcount[p] = 1
        return out

    def retain(self, pages: Sequence[int]) -> None:
        for p in pages:
            if p == TRASH_PAGE:
                continue
            assert self.refcount[p] > 0, f"retain of unowned page {p}"
            self.refcount[p] += 1

    def release(self, pages: Sequence[int]) -> None:
        for p in pages:
            if p == TRASH_PAGE:
                continue
            assert self.refcount[p] > 0, f"double free of page {p}"
            self.refcount[p] -= 1
            if self.refcount[p] == 0:
                self._free.append(p)

    # -- sequence-level helpers ------------------------------------------

    def ensure_capacity(self, seq: SequencePages, new_length: int) -> List[int]:
        """Grow seq's page list to cover new_length tokens; returns pages added."""
        needed = -(-new_length // self.page_size)  # ceil
        added: List[int] = []
        if needed > len(seq.pages):
            added = self.alloc(needed - len(seq.pages))
            seq.pages.extend(added)
        return added

    def free_sequence(self, seq: SequencePages) -> None:
        self.release(seq.pages)
        seq.pages.clear()
        seq.length = 0

    # -- leak detection (engine self-check) ------------------------------

    def check_consistency(self) -> List[str]:
        """Internal allocator invariants; returns human-readable problems.

        Every non-trash page must be exactly one of {free-listed with
        refcount 0, owned with refcount > 0}.  Anything else is a leak or
        a double free in the making.
        """
        problems: List[str] = []
        seen: set = set()
        for p in self._free:
            if p in seen:
                problems.append(f"page {p} duplicated in free list")
            seen.add(p)
            if p == TRASH_PAGE:
                problems.append("trash page in free list")
            elif self.refcount[p] != 0:
                problems.append(
                    f"page {p} free-listed with refcount {self.refcount[p]}"
                )
        for p in range(self.num_pages):
            if p == TRASH_PAGE:
                continue
            rc = int(self.refcount[p])
            if rc < 0:
                problems.append(f"page {p} has negative refcount {rc}")
            elif rc == 0 and p not in seen:
                problems.append(
                    f"page {p} leaked: refcount 0 but not in free list"
                )
        return problems

    def reconcile(
        self, expected: Dict[int, int], repair: bool = False
    ) -> List[str]:
        """Compare refcounts against the owners the caller enumerated.

        `expected` maps page -> number of live references (sequences +
        prefix-cache retains).  Pages whose refcount exceeds that are
        leaked (held by nobody); with `repair` the excess references are
        force-released back to the free list.  Refcounts BELOW the owner
        count mean a double free: repair re-pins them so a future release
        cannot corrupt a stranger's page.
        """
        reports: List[str] = []
        for p in range(self.num_pages):
            if p == TRASH_PAGE:
                continue
            rc = int(self.refcount[p])
            want = expected.get(p, 0)
            if rc == want:
                continue
            kind = "leaked" if rc > want else "double-freed"
            reports.append(
                f"page {p} {kind}: refcount {rc}, {want} live owners"
                + (" (repaired)" if repair else "")
            )
            if not repair:
                continue
            if rc > want:
                self.refcount[p] = want
                if want == 0 and p not in self._free:
                    self._free.append(p)
            else:
                if rc == 0 and p in self._free:
                    self._free.remove(p)
                self.refcount[p] = want
        return reports


def make_kv_pool_arrays(
    cfg: ModelConfig, num_pages: int, page_size: int, dtype=None,
    quantize: str = "",
) -> Tuple[Any, Any]:
    """Allocate the device-side K and V pools.

    Layout is [L, TOTAL_SLOTS, Hkv*D] — heads and head_dim merged into the
    minor (lane) axis.  This keeps the per-slot row a multiple of 128 lanes
    for real model shapes, which the Pallas paged-decode kernel requires for
    its page DMAs (Mosaic slices must be lane-tile aligned); the XLA gather
    path just reshapes gathered rows back to [.., Hkv, D].

    quantize="int8" returns each pool as a models.quant.QTensor pytree
    node: int8 slot rows plus a per-(layer, slot) f32 scale ([L, SLOTS, 1]).
    Per-slot symmetric quantization halves the KV window's HBM traffic —
    the growing share of the step at large batch (COVERAGE roofline) — and
    doubles how many context windows a pool holds (runtime/planner.py).
    Writes quantize rows in-graph at the attention layer; reads dequantize
    inside the gather (models/llama.py).  The QTensor shape rides through
    every jitted program as an ordinary pytree, so the engine's fns don't
    change signature.
    """
    dtype = dtype or cfg.activation_dtype
    shape = (
        cfg.num_layers,
        num_pages * page_size,
        cfg.num_kv_heads * cfg.head_dim,
    )
    if quantize == "int8":
        from ..models.quant import QTensor

        def pool():
            return QTensor(
                q=jnp.zeros(shape, jnp.int8),
                s=jnp.zeros((shape[0], shape[1], 1), jnp.float32),
            )

        return pool(), pool()
    if quantize:
        raise ValueError(f"unknown kv quantize mode {quantize!r}")
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def page_table_array(
    seqs: Sequence[Optional[SequencePages]], max_pages: int
) -> np.ndarray:
    """Stack per-slot page lists into a dense [B, max_pages] int32 table.

    Empty slots (None) and unallocated tail entries point at TRASH_PAGE.
    """
    table = np.full((len(seqs), max_pages), TRASH_PAGE, dtype=np.int32)
    for i, s in enumerate(seqs):
        if s is None:
            continue
        if len(s.pages) > max_pages:
            raise ValueError(
                f"sequence {s.seq_id} has {len(s.pages)} pages > table width {max_pages}"
            )
        table[i, : len(s.pages)] = s.pages
    return table
