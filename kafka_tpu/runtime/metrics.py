"""Serving metrics: the TTFT/TPOT/occupancy counters BASELINE measures.

The reference's observability was print statements (SURVEY §5.1/5.5 — it
even returned zeroed token usage on the agent path).  Here the engine
records real counters as it schedules, the server exports them at
GET /metrics, and bench.py reads the same numbers — one source of truth.

Everything is designed for the single-writer engine thread: recording is
plain attribute math (no locks on the hot path); `snapshot()` is called
from other threads and reads are torn-tolerant (worst case a metric is one
step stale).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Deque, Dict, List, Optional

# Speculative-decoding metric keys (ISSUE 5).  Registry shared with the
# Prometheus exposition layer the same way failpoints.SITES / tracing.SPANS
# are: a static test asserts every name here appears in BOTH
# runtime/metrics.py (this snapshot) and server/prometheus.py (the text
# format), and that neither file invents speculation metrics outside it.
SPECULATION_METRIC_KEYS = (
    "speculation_proposed_tokens",
    "speculation_accepted_tokens",
    "speculation_rejected_tokens",
    "speculation_verify_steps",
    "speculation_acceptance_rate",
    "speculation_accepted_per_step",
)

# Constrained-decoding metric keys (ISSUE 7).  Same registry discipline:
# every key must appear in BOTH this snapshot and server/prometheus.py,
# and neither file may invent constrained_* metrics outside the tuple
# (static check in tests/test_grammar_fsm.py).
CONSTRAINED_METRIC_KEYS = (
    # genuine constrained choice points that awaited a device->host round
    # trip (the host mask-fn micro-batch; ~0 in on-device grammar mode)
    "constrained_roundtrips",
    # over-tight mask rows (no token can satisfy the grammar here): the
    # sampler degrades the row to unconstrained — silently, before this
    # counter existed
    "constrained_mask_overtight",
    # tokens emitted by lanes advancing through the device-resident
    # grammar FSM (zero-roundtrip constrained decoding)
    "constrained_ondevice_tokens",
    # grammar compiles queued/running on the background deferred-compile
    # worker (llm/constrained.py): requests on those schemas take the
    # host-mask path until the table lands.  A PROCESS-WIDE gauge, not a
    # per-engine counter — the DP aggregate reports it once, unsummed.
    "constrained_compile_pending",
)

# Tiered-KV-cache metric keys (ISSUE 9, runtime/kv_tier.py snapshot()).
# Same registry discipline as the families above: every key appears in
# BOTH this module's snapshot section and server/prometheus.py, and
# neither file invents kv-tier metrics outside the tuple (static check in
# tests/test_kv_tier.py).  Gauges (host/disk occupancy) sum meaningfully
# across DP replicas — each replica owns an independent tier.
KV_TIER_METRIC_KEYS = (
    "host_budget_bytes",
    "host_bytes",
    "host_runs",
    "disk_bytes",
    "disk_runs",
    "demotions",
    "pages_demoted",
    "bytes_demoted",
    "demote_failures",
    "promotions",
    "pages_promoted",
    "bytes_promoted",
    "promote_failures",
    "host_evictions",
    "disk_spills",
    "disk_loads",
)


def _copy_samples(dq) -> List[float]:
    """Snapshot a histogram deque that another thread may be appending to.

    CPython deque iteration raises RuntimeError if the owner (the engine
    thread) appends mid-copy — even at maxlen.  Reads are torn-tolerant by
    design, so just retry; losing a snapshot entirely is the only failure
    worth avoiding.
    """
    for _ in range(8):
        try:
            return list(dq)
        except RuntimeError:
            continue
    return []


def _percentiles(samples: List[float], pts=(50, 90, 99)) -> Dict[str, float]:
    if not samples:
        return {f"p{p}": 0.0 for p in pts}
    s = sorted(samples)
    out = {}
    for p in pts:
        # nearest-rank: smallest value with at least p% of samples <= it
        idx = min(len(s) - 1, max(0, -(-p * len(s) // 100) - 1))
        out[f"p{p}"] = s[idx]
    return out


@dataclasses.dataclass
class ReplicaSupervisorMetrics:
    """Counters owned by the DP replica supervisor (runtime/dp_router.py).

    Single-writer like EngineMetrics: the engine/worker thread that drives
    DataParallelEngines.step() is the only mutator; snapshot() is read
    from serving threads and is torn-tolerant."""

    quarantines: int = 0  # circuit-breaker trips (healthy/probation -> out)
    readmits: int = 0  # probation -> healthy promotions (warm re-admit)
    waiting_migrated: int = 0  # queued requests moved off a sick replica
    affinity_resteered: int = 0  # prefix_key pins moved to a new replica
    rebuilds: int = 0  # topology rebuilds (dp resize / replica loss)

    def snapshot(self) -> Dict[str, int]:
        return {
            "quarantines": self.quarantines,
            "readmits": self.readmits,
            "waiting_migrated": self.waiting_migrated,
            "affinity_resteered": self.affinity_resteered,
            "rebuilds": self.rebuilds,
        }


@dataclasses.dataclass
class EngineMetrics:
    """Counters owned by the engine; histograms keep the last N samples."""

    window: int = 512  # samples kept per histogram

    requests_submitted: int = 0
    requests_finished: int = 0
    requests_cancelled: int = 0
    requests_preempted: int = 0
    # lifecycle hardening counters: deadline expiries, admission-gate
    # rejections (HTTP 429), and engine-failure terminations
    requests_timeout: int = 0
    requests_rejected: int = 0
    requests_failed: int = 0
    # waiting-queue gauge, recorded once per scheduler iteration
    queue_depth: int = 0
    queue_depth_peak: int = 0
    prompt_tokens: int = 0
    generated_tokens: int = 0
    decode_steps: int = 0
    decode_busy_slots: int = 0  # sum over steps -> occupancy = /steps/B
    # Tokens dispatched for a lane whose request was already finished when
    # the fetch matured (stop token discovered in flight, or a cancel) —
    # the cost of the pipelined/fused dispatch running ahead of drain.
    # These occupied batch slots; wasted/(generated+wasted) is the
    # throughput tax.  RENAMED from speculative_wasted_tokens (PR 5): this
    # is FETCH-PIPELINE waste, not speculative-decoding waste — the old
    # /metrics JSON keys survive one release as deprecated aliases.
    fetch_pipeline_wasted_tokens: int = 0
    # Real speculative decoding (draft-free n-gram proposals + batched
    # verify, runtime/speculative.py + engine verify step).  proposed
    # counts candidate tokens at dispatch; accepted/rejected reconcile at
    # drain (a discarded entry counts all its candidates rejected), so
    # proposed == accepted + rejected + in-flight and every counter stays
    # monotone across preemption/rollback.
    speculation_proposed_tokens: int = 0
    speculation_accepted_tokens: int = 0
    speculation_rejected_tokens: int = 0
    speculation_verify_steps: int = 0  # verify dispatches (1 per step)
    # constrained decoding (CONSTRAINED_METRIC_KEYS): awaited host
    # round trips, over-tight mask degrades, and device-FSM tokens
    constrained_roundtrips: int = 0
    constrained_mask_overtight: int = 0
    constrained_ondevice_tokens: int = 0

    def __post_init__(self) -> None:
        self.ttft_ms: Deque[float] = collections.deque(maxlen=self.window)
        self.tpot_ms: Deque[float] = collections.deque(maxlen=self.window)
        # TTFT decomposition (queue wait / prefill / fetch+emit) — the
        # three phases whose confounding made r4's oversubscribed-TTFT
        # numbers one unexplainable figure (VERDICT r4 weak #3)
        self.ttft_queue_ms: Deque[float] = collections.deque(maxlen=self.window)
        self.ttft_prefill_ms: Deque[float] = collections.deque(maxlen=self.window)
        self.ttft_fetch_ms: Deque[float] = collections.deque(maxlen=self.window)
        # token-emission cadence as the client sees it: how many tokens
        # arrive together when the fetch pipeline pops (burst size) and how
        # far apart those arrivals are (gap) — the honest view of stream
        # smoothness that step-interval TPOT cannot give under pipelining
        self.burst_tokens: Deque[float] = collections.deque(maxlen=self.window)
        self.burst_gap_ms: Deque[float] = collections.deque(maxlen=self.window)
        self._last_burst_t: Optional[float] = None
        self._last_step_t: Optional[float] = None
        self._last_step_steps: int = 1
        self._started = time.monotonic()

    # -- engine-thread recording ----------------------------------------

    def record_submit(self, prompt_tokens: int) -> None:
        self.requests_submitted += 1
        self.prompt_tokens += prompt_tokens

    def record_first_token(self, latency_s: float) -> None:
        self.ttft_ms.append(latency_s * 1e3)

    def record_ttft_breakdown(self, submit, prefill_start, first_dispatch,
                              first_token) -> None:
        """Split one request's TTFT into queue / prefill / fetch phases.
        Missing stamps (cancelled mid-phase, legacy paths) record nothing."""
        if None in (submit, prefill_start, first_dispatch, first_token):
            return
        self.ttft_queue_ms.append((prefill_start - submit) * 1e3)
        self.ttft_prefill_ms.append((first_dispatch - prefill_start) * 1e3)
        self.ttft_fetch_ms.append((first_token - first_dispatch) * 1e3)

    def record_token(self) -> None:
        self.generated_tokens += 1

    def record_wasted_token(self, n: int = 1) -> None:
        self.fetch_pipeline_wasted_tokens += n

    def record_verify_dispatch(self, proposed: int) -> None:
        """One verify step dispatched with `proposed` candidate tokens."""
        self.speculation_verify_steps += 1
        self.speculation_proposed_tokens += proposed

    def record_verify_drain(self, accepted: int, rejected: int) -> None:
        """One proposing lane's verify result reconciled at drain."""
        self.speculation_accepted_tokens += accepted
        self.speculation_rejected_tokens += rejected

    def record_decode_step(self, busy_slots: int, steps: int = 1) -> None:
        """steps>1 = a fused multi-step dispatch.  The gap between this
        call and the previous one spans the PREVIOUS dispatch's tokens
        (back-to-back dispatches overlap that dispatch's device execution),
        so the TPOT sample divides by the steps recorded last time."""
        now = time.monotonic()
        if self._last_step_t is not None:
            # inter-step time while decoding == per-token latency for every
            # active stream (the definition of TPOT under continuous
            # batching); long gaps (idle engine) are not TPOT — drop them
            dt = (now - self._last_step_t) * 1e3 / self._last_step_steps
            if dt < 2_000:
                self.tpot_ms.append(dt)
        self._last_step_t = now
        self._last_step_steps = steps
        self.decode_steps += steps
        self.decode_busy_slots += busy_slots * steps

    def mark_idle(self) -> None:
        """The engine drained: the gap until the next decode step is idle
        time, not TPOT (nor a burst gap) — drop both timing baselines."""
        self._last_step_t = None
        self._last_burst_t = None

    def record_emit_burst(self, n_tokens: int) -> None:
        now = time.monotonic()
        self.burst_tokens.append(float(n_tokens))
        if self._last_burst_t is not None:
            gap = (now - self._last_burst_t) * 1e3
            if gap < 2_000:
                self.burst_gap_ms.append(gap)
        self._last_burst_t = now

    def record_finish(self, reason: Optional[str]) -> None:
        if reason == "cancelled":
            self.requests_cancelled += 1
        elif reason == "timeout":
            self.requests_timeout += 1
        elif reason is not None and reason.startswith("error"):
            self.requests_failed += 1
        else:
            self.requests_finished += 1

    def record_preempt(self) -> None:
        self.requests_preempted += 1

    def record_rejected(self) -> None:
        self.requests_rejected += 1

    def record_queue_depth(self, depth: int) -> None:
        self.queue_depth = depth
        if depth > self.queue_depth_peak:
            self.queue_depth_peak = depth

    def recent_tpot_s(self) -> Optional[float]:
        """Median of the recent TPOT window, in seconds (None = no data).
        Safe to call from other threads (torn-tolerant snapshot)."""
        samples = _copy_samples(self.tpot_ms)
        if not samples:
            return None
        return sorted(samples)[len(samples) // 2] / 1e3

    # -- cross-thread export --------------------------------------------

    def constrained_snapshot(self) -> Dict[str, int]:
        """The constrained-decoding section (CONSTRAINED_METRIC_KEYS)."""
        try:
            from ..llm.constrained import compile_pending
            pending = compile_pending()
        except Exception:
            pending = 0  # import-light contexts (no llm tier loaded)
        return {
            "constrained_roundtrips": self.constrained_roundtrips,
            "constrained_mask_overtight": self.constrained_mask_overtight,
            "constrained_ondevice_tokens": self.constrained_ondevice_tokens,
            "constrained_compile_pending": pending,
        }

    def speculation_snapshot(self) -> Dict[str, object]:
        """The speculative-decoding section (SPECULATION_METRIC_KEYS):
        raw monotone counters plus the two derived rates dashboards want
        (acceptance = accepted/proposed over drained rounds; accepted per
        verify step = the amortization factor the weight-stream gains)."""
        drained = (self.speculation_accepted_tokens
                   + self.speculation_rejected_tokens)
        return {
            "speculation_proposed_tokens": self.speculation_proposed_tokens,
            "speculation_accepted_tokens": self.speculation_accepted_tokens,
            "speculation_rejected_tokens": self.speculation_rejected_tokens,
            "speculation_verify_steps": self.speculation_verify_steps,
            "speculation_acceptance_rate": round(
                self.speculation_accepted_tokens / drained, 4
            ) if drained else 0.0,
            "speculation_accepted_per_step": round(
                self.speculation_accepted_tokens
                / self.speculation_verify_steps, 3
            ) if self.speculation_verify_steps else 0.0,
        }

    def snapshot(self, engine=None) -> Dict[str, object]:
        up = time.monotonic() - self._started
        snap: Dict[str, object] = {
            "uptime_s": round(up, 1),
            "requests": {
                "submitted": self.requests_submitted,
                "finished": self.requests_finished,
                "cancelled": self.requests_cancelled,
                "preempted": self.requests_preempted,
                "timeout": self.requests_timeout,
                "rejected": self.requests_rejected,
                "failed": self.requests_failed,
            },
            "queue": {
                "depth": self.queue_depth,
                "peak": self.queue_depth_peak,
            },
            "tokens": {
                "prompt": self.prompt_tokens,
                "generated": self.generated_tokens,
                "generated_per_s": round(self.generated_tokens / up, 2)
                if up > 0 else 0.0,
                "fetch_pipeline_wasted": self.fetch_pipeline_wasted_tokens,
                "fetch_pipeline_waste_frac": round(
                    self.fetch_pipeline_wasted_tokens
                    / (self.generated_tokens
                       + self.fetch_pipeline_wasted_tokens),
                    4,
                ) if (self.generated_tokens
                      + self.fetch_pipeline_wasted_tokens) else 0.0,
            },
            "ttft_ms": {k: round(v, 2) for k, v in
                        _percentiles(_copy_samples(self.ttft_ms)).items()},
            "ttft_breakdown_ms": {
                name: {k: round(v, 2) for k, v in
                       _percentiles(_copy_samples(dq)).items()}
                for name, dq in (
                    ("queue_wait", self.ttft_queue_ms),
                    ("prefill", self.ttft_prefill_ms),
                    ("first_fetch", self.ttft_fetch_ms),
                )
            },
            # legacy top-level key kept for dashboards; the full family
            # lives in the "constrained" section
            "constrained_roundtrips": self.constrained_roundtrips,
            "constrained": self.constrained_snapshot(),
            "speculation": self.speculation_snapshot(),
            "tpot_ms": {k: round(v, 2) for k, v in
                        _percentiles(_copy_samples(self.tpot_ms)).items()},
            "decode": {
                "steps": self.decode_steps,
                "batch_occupancy": round(
                    self.decode_busy_slots / self.decode_steps, 3
                ) if self.decode_steps else 0.0,
            },
            "emission": {
                "burst_tokens": {
                    k: round(v, 2) for k, v in
                    _percentiles(_copy_samples(self.burst_tokens)).items()
                },
                "burst_gap_ms": {
                    k: round(v, 2) for k, v in
                    _percentiles(_copy_samples(self.burst_gap_ms)).items()
                },
            },
        }
        # (the speculative_wasted_* aliases the PR 5 rename kept for one
        # release are gone — fetch_pipeline_wasted_* is the only spelling;
        # README "Metrics rename" documents the removal)
        if engine is not None:
            snap["engine"] = {
                "active": engine.num_active,
                "waiting": len(engine.waiting),
                "in_flight_fetches": len(engine._pending),
                "pages_total": engine.pool.num_pages,
                "pages_free": engine.pool.free_pages,
                "pages_in_use": engine.pool.num_pages - 1
                - engine.pool.free_pages,
                "max_batch": engine.ecfg.max_batch,
                "attention_backend": engine.cfg.attention_backend,
                "rtt_est_ms": round(engine._rtt_est * 1e3, 3),
            }
            if engine.prefix_cache is not None:
                pc = engine.prefix_cache
                snap["prefix_cache"] = {
                    # radix tree shape: nodes (page-aligned token runs) and
                    # the pages they retain ("entries" keeps the legacy
                    # name for the node count)
                    "entries": len(pc),
                    "nodes": len(pc),
                    "cached_pages": pc.total_pages,
                    "hits": pc.hits,
                    "misses": pc.misses,
                    "tokens_reused": pc.tokens_reused,
                    "cross_thread_hits": pc.cross_thread_hits,
                    "host_tier_hits": pc.host_tier_hits,
                    "host_nodes": pc.host_nodes,
                    "host_pages": pc.host_pages,
                    "evictions": pc.evictions,
                    "pages_evicted": pc.pages_evicted,
                }
            tier = getattr(engine, "kv_tier", None)
            if tier is not None:
                # tiered KV cache (KV_TIER_METRIC_KEYS)
                snap["kv_tier"] = tier.snapshot()
        return snap
