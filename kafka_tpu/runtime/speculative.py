"""Draft-free speculative decoding: host-side n-gram prompt-lookup proposer.

Decode on this engine is HBM-bandwidth-bound — every step streams the full
weights to advance each lane ONE token.  Speculative decoding (Leviathan et
al., 2023) amortizes one weight-stream over several tokens: propose a run
of K candidate tokens, verify all of them (plus the bonus token after the
last accepted one) in ONE [B, K+1]-query device dispatch, keep the longest
prefix the model itself would have produced.

The proposer here is *draft-free* prompt lookup (Saxena, 2023): the agent
workload this framework serves echoes file contents, JSON tool results and
code spans back into the generation, so candidate runs come for free from a
suffix match over the lane's OWN token history — no draft model, no extra
HBM residency, and nothing that perturbs the static-shape continuous-
batching invariant (non-proposing lanes ride the same verify dispatch
masked down to ordinary 1-token decode).

Acceptance rule (engine._build_verify_fn): the verify step samples every
position with the SAME per-(seed, position) key the sequential decode path
uses, and accepts candidates exactly while `sample == candidate`.  The
emitted tokens are therefore *literally the sequential path's samples* —
greedy output is bit-identical and sampled output follows the target
distribution at any temperature by construction (this is the exact-match
special case of Leviathan rejection sampling for a point-mass draft).

This module is pure host-side bookkeeping: the rolling n-gram index and the
per-lane acceptance EWMA that throttles proposing for lanes where
speculation is losing (adaptive K).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

# Largest/smallest suffix n-gram the proposer anchors on.  Longer anchors
# first: a 3-token match is far more predictive than a 2-token match in
# byte/token streams, and both lookups are O(1) dict probes.
NGRAM_MAX = 3
NGRAM_MIN = 2

# Adaptive-K throttle: once the acceptance EWMA (accepted/proposed per
# verify round) falls below the floor, the lane reverts to plain decode and
# re-probes after PROBE_TOKENS more drained tokens (repetition often comes
# in phases: a tool-echo span follows free prose).
ACCEPT_FLOOR = 0.2
ACCEPT_EWMA_ALPHA = 0.3
PROBE_TOKENS = 64

# Prompt indexing is AMORTIZED: construction/propose index at most this
# many tokens per call, so admitting a 100k-token prompt never stalls the
# single engine worker thread (eager indexing measured ~4us/token — ~0.4s
# of frozen token emission for every in-flight stream per long admission).
# A warming lane simply rides plain decode until its index catches up.
INDEX_BUDGET = 2048


class LaneSpeculator:
    """Per-lane n-gram index + acceptance controller.

    Single-writer (the engine thread).  `hist` mirrors the lane's token
    stream — prompt at construction, then one `push()` per DRAINED output
    token — so `propose()` always anchors on a fully-known tail (the
    engine only proposes for lanes with no in-flight dispatches).
    """

    __slots__ = ("hist", "_index", "_indexed", "accept_ewma", "_probe_at",
                 "proposed", "accepted")

    def __init__(self, prompt_ids: Sequence[int]):
        self.hist: List[int] = [int(t) for t in prompt_ids]
        # n-gram -> FIRST continuation position (the token index right
        # after the n-gram's earliest occurrence — the classic prompt-
        # lookup anchor).  Earliest beats most-recent for run length: on a
        # periodic tail the most recent occurrence is one step back and
        # offers a 1-token continuation, while the first offers the whole
        # repeated span.  A cheap rolling index: each position inserts
        # NGRAM_MAX-NGRAM_MIN+1 small-tuple keys at most once each, fed
        # INDEX_BUDGET tokens at a time (amortized over propose calls) so
        # a long prompt never stalls the engine thread at submit.  Memory
        # is ~2 dict entries per history token, bounded by the attention
        # window the lane itself is bounded by.
        self._index: Dict[Tuple[int, ...], int] = {}
        self._indexed = 0  # hist prefix the index covers
        self.accept_ewma = 1.0  # optimistic: every lane gets a first shot
        self._probe_at: Optional[int] = None  # hist len gating a re-probe
        self.proposed = 0
        self.accepted = 0

    def push(self, token: int) -> None:
        self.hist.append(token)
        self._catch_up()

    def _catch_up(self, budget: int = INDEX_BUDGET) -> bool:
        """Index up to `budget` more history tokens; True when the index
        covers the whole history (a drained lane is usually 1 behind)."""
        hist = self.hist
        end = self._indexed
        stop = min(len(hist), end + budget)
        index = self._index
        while end < stop:
            end += 1
            for n in range(NGRAM_MIN, NGRAM_MAX + 1):
                if end >= n:
                    index.setdefault(tuple(hist[end - n:end]), end)
        self._indexed = end
        return end == len(hist)

    def _continuation_at(self) -> Optional[int]:
        """Position right after the EARLIEST occurrence of the current
        suffix (None = no earlier occurrence).  Longest anchor wins."""
        hist = self.hist
        end = len(hist)
        for n in range(NGRAM_MAX, NGRAM_MIN - 1, -1):
            if end < n:
                continue
            pos = self._index.get(tuple(hist[end - n:end]))
            # pos == end means the only occurrence is the suffix itself
            if pos is not None and pos < end:
                return pos
        return None

    def propose(self, k_max: int) -> List[int]:
        """Candidate continuation of up to k_max tokens ([] = don't
        speculate this lane this round)."""
        if k_max <= 0:
            return []
        if not self._catch_up():
            # long prompt still being indexed (amortized): plain decode
            # until the index covers the whole history — an anchor over a
            # partial index could miss the earliest occurrence
            return []
        if self.accept_ewma < ACCEPT_FLOOR:
            # throttled: speculation has been losing on this lane — plain
            # decode until the periodic re-probe
            if self._probe_at is None or len(self.hist) < self._probe_at:
                return []
        pos = self._continuation_at()
        if pos is None:
            return []
        return self.hist[pos:pos + k_max]

    def observe(self, accepted: int, proposed: int) -> None:
        """Account one drained verify round (proposed >= 1)."""
        self.proposed += proposed
        self.accepted += accepted
        rate = accepted / proposed
        self.accept_ewma = (
            (1 - ACCEPT_EWMA_ALPHA) * self.accept_ewma
            + ACCEPT_EWMA_ALPHA * rate
        )
        if self.accept_ewma < ACCEPT_FLOOR:
            self._probe_at = len(self.hist) + PROBE_TOKENS
        else:
            self._probe_at = None
