"""Memory-fit planner: pure arithmetic over model + engine + mesh shapes.

A serving framework must answer "does this config fit this topology, and at
what concurrency?" *before* anyone buys the topology.  The reference never
had to (its LLM compute was a remote gateway, src/llm/portkey.py); a local
TPU engine does.  This module computes per-device HBM bytes for a
(ModelConfig, engine shape, mesh) triple using THE SAME placement rules the
engine actually applies:

* weights follow parallel/sharding.py's PartitionSpecs — including the
  grouped-GQA factorization (parallel/mesh.py factor_tp_for_kv) that shards
  kv projections and the KV pool over the largest common divisor of the
  tensor degree and num_kv_heads, replicating each kv head only across its
  tq-group (70B at degree 16: 8-way kv shard, 2 chips per head — 8x less
  per-chip KV than the full replication this planner charged before);
* the KV pool is the [L, num_pages * page_size, Hkv*D] pair of
  runtime/kv_cache.py, k and v, layer axis split over pp
  (parallel/pipeline.py stages), head axis over gcd(tp, Hkv) — the
  grouped-GQA kv sub-axis (tq groups replicate);
* int8 weight quantization (models/quant.py) stores 1 byte/param + an f32
  scale per output channel; int8 KV halves pool bytes + per-page f32 scales.

Activation peaks are *estimates* (XLA's scratch is its own business), sized
from the dominant live tensors: the [S, V/tp] f32 prefill logits block, the
flash-prefill window gather, and the decode-time [B, V] f32 logits +
sampling workspace.  A fragmentation/scratch reserve (default 8%) absorbs
what the formulas do not model; `tests/test_planner.py` pins the known
ground truths (8B bf16 does NOT fit one v5e chip, 8B int8 DOES — both
observed on real hardware in round 4).

Known HBM budgets (public datasheet numbers):
  v5e  (v5 lite): 16 GiB/chip
  v5p:            95 GiB/chip
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ..models.config import ModelConfig

GiB = 1024**3
MiB = 1024**2

# chip generation -> HBM bytes per chip
HBM_BYTES = {
    "v5e": 16 * GiB,
    "v5p": 95 * GiB,
    "v6e": 32 * GiB,
    "v4": 32 * GiB,
}

# chip generation -> (peak dense bf16 FLOP/s, HBM bytes/s) per chip —
# the roofline the device-utilization estimator (ISSUE 10) divides the
# planner's modeled per-dispatch flop/byte costs by.  Public datasheet
# numbers, like HBM_BYTES above.
CHIP_PEAKS = {
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v6e": (918e12, 1640e9),
    "v4": (275e12, 1228e9),
}

PEAK_TFLOPS_ENV = "KAFKA_TPU_PEAK_TFLOPS"
PEAK_HBM_GBPS_ENV = "KAFKA_TPU_PEAK_HBM_GBPS"

_DTYPE_BYTES = {"bfloat16": 2, "float32": 4, "float16": 2, "int8": 1}


def _bytes(dtype: str) -> int:
    return _DTYPE_BYTES[dtype]


def _kv_shard(cfg: ModelConfig, tp: int, kv_shard: Optional[int] = None) -> int:
    """kv-head shard factor — delegates to parallel/mesh.py
    factor_tp_for_kv so the plan charges exactly what the engine places:
    the tensor degree factorizes into tp_kv * tq with tp_kv =
    gcd(degree, Hkv); kv params and the pool shard tp_kv-ways and
    replicate only across the tq groups (grouped GQA head-sharing).  A
    degree sharing no factor with Hkv degrades to full replication.

    `kv_shard` overrides the grouped default for configs where the mesh
    keeps the plain tensor axis (ulysses CP, pp stages) —
    plan_for_serving resolves it via the SAME resolve_tensor_axes call
    the server uses, so plan and placement cannot drift."""
    if kv_shard is not None:
        return kv_shard
    from ..parallel.mesh import factor_tp_for_kv

    return factor_tp_for_kv(tp, cfg.num_kv_heads)[0]


def hbm_for_device(dev) -> Optional[int]:
    """Best-effort HBM budget for a live jax device: the runtime's
    bytes_limit when reported, else the datasheet number for the chip
    generation parsed from device_kind."""
    stats = getattr(dev, "memory_stats", lambda: None)() or {}
    if stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    if dev.platform != "tpu":
        return None
    kind = getattr(dev, "device_kind", "").lower()
    if "v5p" in kind:
        return HBM_BYTES["v5p"]
    if "v6" in kind:
        return HBM_BYTES["v6e"]
    if "lite" in kind or "v5e" in kind or "v5" in kind:
        return HBM_BYTES["v5e"]  # plain "v5": conservative (lite) budget
    if "v4" in kind:
        return HBM_BYTES["v4"]
    return None  # unknown generation: skip validation, never misjudge it


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """Per-device byte budget for one serving configuration."""

    model: str
    mesh: Dict[str, int]              # {"tp":..,"sp":..,"pp":..,"ep":..}
    hbm_bytes: int                    # budget per chip
    reserve_frac: float               # scratch/fragmentation allowance
    weight_bytes: int                 # per device
    kv_pool_bytes: int                # per device (both k and v)
    activation_bytes: int             # estimated peak live activations
    kv_replicated: bool               # kv not sharded the full tensor
                                      # degree (gcd(tp, Hkv) < tp): pool
                                      # replicated across tq groups
    kv_bytes_per_token: int           # per device, k+v, all layers
    window_tokens: int                # configured attention window
    # Machine-readable grouped tp×tq factorization (the layout the bytes
    # above are charged under): kv params + pool shard kv_shard-ways and
    # replicate across tq groups.  mesh["tp"] stays the REQUESTED tensor
    # degree (= kv_shard * tq when grouped); consumers should read these
    # fields, not parse the free-text notes.
    kv_shard: int = 1
    tq: int = 1
    # On-device constrained-decoding grammar tables (ISSUE 7): the
    # KAFKA_TPU_GRAMMAR_TABLE_MB reservation, replicated per device.  The
    # engine's _GrammarTables.register enforces the same figure as a
    # COMBINED budget over all live grammars' padded tables (over-budget
    # registrations degrade to the host mask path), so this charge is the
    # true worst case.  0 when on-device grammar is disabled.
    grammar_table_bytes: int = 0
    # Tiered KV cache host-pool budget (ISSUE 9): HOST RAM per engine
    # replica (KAFKA_TPU_KV_HOST_TIER_MB), charged here so a deployment
    # plan states the full memory footprint — but deliberately NOT part
    # of total_bytes, which is the per-chip HBM budget.  0 = tier off.
    kv_host_tier_bytes: int = 0
    notes: str = ""

    @property
    def total_bytes(self) -> int:
        return (self.weight_bytes + self.kv_pool_bytes
                + self.activation_bytes + self.grammar_table_bytes)

    @property
    def usable_bytes(self) -> int:
        return int(self.hbm_bytes * (1.0 - self.reserve_frac))

    @property
    def fits(self) -> bool:
        return self.total_bytes <= self.usable_bytes

    @property
    def headroom_bytes(self) -> int:
        return self.usable_bytes - self.total_bytes

    @property
    def max_concurrent_windows(self) -> int:
        """How many FULL attention windows of KV the leftover HBM holds —
        the honest "max concurrent N-token threads" number (weights and
        activations charged first; the configured pool is not)."""
        free = self.usable_bytes - self.weight_bytes - self.activation_bytes
        per_window = self.kv_bytes_per_token * self.window_tokens
        return max(0, free // per_window) if per_window else 0

    def summary(self) -> Dict[str, object]:
        return {
            "model": self.model,
            "mesh": self.mesh,
            "hbm_gib": round(self.hbm_bytes / GiB, 2),
            "weight_gib": round(self.weight_bytes / GiB, 3),
            "kv_pool_gib": round(self.kv_pool_bytes / GiB, 3),
            "activation_gib": round(self.activation_bytes / GiB, 3),
            "total_gib": round(self.total_bytes / GiB, 3),
            "usable_gib": round(self.usable_bytes / GiB, 3),
            "fits": self.fits,
            "headroom_gib": round(self.headroom_bytes / GiB, 3),
            "kv_replicated": self.kv_replicated,
            "kv_shard": self.kv_shard,
            "tq": self.tq,
            "grammar_table_mib": round(self.grammar_table_bytes / MiB, 2),
            "kv_host_tier_mib": round(self.kv_host_tier_bytes / MiB, 2),
            "window_tokens": self.window_tokens,
            "max_concurrent_windows": self.max_concurrent_windows,
            "notes": self.notes,
        }


def weight_bytes_per_device(
    cfg: ModelConfig,
    *,
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    quantize: str = "",
    kv_shard: Optional[int] = None,
) -> int:
    """Per-device weight bytes under parallel/sharding.py's rules."""
    h, f, d = cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
    hq, hkv = cfg.num_heads, cfg.num_kv_heads
    L = cfg.num_layers
    wb = _bytes(cfg.dtype)
    int8 = quantize == "int8"

    def mat(rows: int, cols: int, shard: int) -> int:
        """One weight matrix sharded `shard`-ways; int8 = 1B + f32 scale
        per output channel (quant.py: scale shape keeps the out axis)."""
        n = rows * cols // shard
        return n + (cols // shard) * 4 if int8 else n * wb

    kv_shard = _kv_shard(cfg, tp, kv_shard)

    per_layer = (
        mat(h, hq * d, tp)            # wq
        + 2 * mat(h, hkv * d, kv_shard)  # wk, wv
        + mat(hq * d, h, tp)          # wo (row-parallel: heads on tp)
        + 2 * h * wb                  # norms (replicated)
    )
    if cfg.is_moe:
        e_shard = ep if (ep > 1 and cfg.num_experts % ep == 0) else 1
        per_layer += h * cfg.num_experts * wb  # router, replicated
        per_layer += cfg.num_experts // e_shard * (
            2 * mat(h, f, tp) + mat(f, h, tp)
        )
    else:
        per_layer += 2 * mat(h, f, tp) + mat(f, h, tp)

    total = per_layer * L // pp
    # embed replicated (lookup local); untied lm_head tp-sharded over V
    total += mat(cfg.vocab_size, h, 1) if int8 else cfg.vocab_size * h * wb
    total += h * wb  # final norm
    if not cfg.tie_word_embeddings:
        total += mat(h, cfg.vocab_size, tp)
    return total


def kv_pool_bytes_per_device(
    cfg: ModelConfig,
    *,
    num_pages: int,
    page_size: int,
    tp: int = 1,
    pp: int = 1,
    kv_dtype: str = "bfloat16",
    kv_shard: Optional[int] = None,
) -> int:
    """Both pool arrays (k + v), [L/pp, num_pages*page_size, Hkv*D]."""
    hkv_d = cfg.num_kv_heads * cfg.head_dim
    kv_shard = _kv_shard(cfg, tp, kv_shard)
    slots = num_pages * page_size
    per = cfg.num_layers // pp * slots * hkv_d // kv_shard
    b = per * _bytes(kv_dtype) * 2
    if kv_dtype == "int8":
        # per-slot f32 scales, k and v (int8 KV quantization tier)
        b += cfg.num_layers // pp * slots * 2 * 4
    return b


def kv_bytes_per_token(
    cfg: ModelConfig, *, tp: int = 1, pp: int = 1,
    kv_dtype: str = "bfloat16", kv_shard: Optional[int] = None,
) -> int:
    kv_shard = _kv_shard(cfg, tp, kv_shard)
    return (
        cfg.num_layers // pp
        * cfg.num_kv_heads * cfg.head_dim // kv_shard
        * _bytes(kv_dtype) * 2
    )


def activation_bytes_estimate(
    cfg: ModelConfig,
    *,
    max_batch: int,
    prefill_bucket: int,
    window: int,
    tp: int = 1,
    sp: int = 1,
) -> int:
    """Peak live activations, from the dominant tensors.

    Prefill (chunk S over sp ranks, heads/F over tp):
      logits block  S/sp * V/tp * 4   (f32, the [S, V] einsum output)
      hidden trio   S/sp * (H + 2*F/tp) * 2
      window gather S * Hkv*D * 2 * 2 (XLA fallback reads k+v windows;
                    the flash kernel streams pages instead, but plan for
                    the portable path)
    Decode: B * V * 4 * 3 (logits + top-k sort workspace ~2 copies).
    """
    V, H, F = cfg.vocab_size, cfg.hidden_size, cfg.intermediate_size
    hkv_d = cfg.num_kv_heads * cfg.head_dim
    s_local = max(1, prefill_bucket // max(sp, 1))
    prefill = (
        s_local * (V // tp) * 4
        + s_local * (H + 2 * F // tp) * 2
        + window * hkv_d * 2 * 2
    )
    decode = max_batch * V * 4 * 3 + max_batch * window * hkv_d * 2 * 2
    return max(prefill, decode)


def plan_memory(
    cfg: ModelConfig,
    *,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    ep: int = 1,
    num_pages: int,
    page_size: int,
    max_pages_per_seq: int,
    max_batch: int,
    prefill_bucket: int = 512,
    quantize: str = "",
    kv_dtype: str = "bfloat16",
    hbm_bytes: Optional[int] = None,
    chip: str = "v5e",
    reserve_frac: float = 0.08,
    kv_shard: Optional[int] = None,
    grammar_table_bytes: Optional[int] = None,
    kv_host_tier_bytes: int = 0,
) -> MemoryPlan:
    if hbm_bytes is None:
        hbm_bytes = HBM_BYTES[chip]
    if grammar_table_bytes is None:
        # charge the on-device constrained-decoding table reservation
        # (the compiler caps artifacts at this size; tables replicate
        # per device) unless the feature is disabled
        from ..llm.constrained import (
            _grammar_table_cap_bytes,
            grammar_ondevice_enabled,
        )

        grammar_table_bytes = (
            _grammar_table_cap_bytes() if grammar_ondevice_enabled() else 0
        )
    kv_shard = _kv_shard(cfg, tp, kv_shard)
    kv_replicated = tp > 1 and kv_shard < tp
    window = max_pages_per_seq * page_size
    plan = MemoryPlan(
        model=cfg.name,
        mesh={"tp": tp, "sp": sp, "pp": pp, "ep": ep},
        hbm_bytes=hbm_bytes,
        reserve_frac=reserve_frac,
        weight_bytes=weight_bytes_per_device(
            cfg, tp=tp, pp=pp, ep=ep, quantize=quantize, kv_shard=kv_shard
        ),
        kv_pool_bytes=kv_pool_bytes_per_device(
            cfg, num_pages=num_pages, page_size=page_size, tp=tp, pp=pp,
            kv_dtype=kv_dtype, kv_shard=kv_shard,
        ),
        activation_bytes=activation_bytes_estimate(
            cfg, max_batch=max_batch, prefill_bucket=prefill_bucket,
            window=window, tp=tp, sp=sp,
        ),
        kv_replicated=kv_replicated,
        kv_bytes_per_token=kv_bytes_per_token(
            cfg, tp=tp, pp=pp, kv_dtype=kv_dtype, kv_shard=kv_shard
        ),
        window_tokens=window,
        # unconditional: tp = kv_shard * tq always holds, so kv_shard=1
        # with tp=8 reports tq=8 (full 8-way replication), not tq=1
        kv_shard=kv_shard,
        tq=tp // kv_shard,
        grammar_table_bytes=grammar_table_bytes,
        kv_host_tier_bytes=kv_host_tier_bytes,
        notes=(
            (
                f"grouped GQA layout: tensor degree {tp} factorizes "
                f"tp={kv_shard} x tq={tp // kv_shard}; kv params+pool "
                f"shard {kv_shard}-ways, each kv head replicated on "
                f"{tp // kv_shard} chips (parallel/mesh.py "
                "factor_tp_for_kv)"
                if kv_shard > 1 else
                "kv params+pool fully replicated per chip: the mesh "
                f"keeps the plain tensor axis (degree {tp}) and it does "
                f"not divide num_kv_heads ({cfg.num_kv_heads})"
            )
            if kv_replicated else ""
        ),
    )
    return plan


def device_peaks(dev) -> tuple:
    """(peak FLOP/s, peak HBM bytes/s, source) roofline for a live jax
    device — the denominator of the MFU / HBM-bandwidth-utilization
    estimator (ISSUE 10).

    KAFKA_TPU_PEAK_TFLOPS / KAFKA_TPU_PEAK_HBM_GBPS override everything
    (CPU runs, unlisted chip revisions, derated shared machines); else
    the datasheet table by device_kind.  Unknown generations return
    (None, None, "unknown") — the estimator then reports achieved
    FLOP/s and GB/s without ratios rather than inventing a roofline.
    """
    import os as _os

    env_tf = _os.environ.get(PEAK_TFLOPS_ENV)
    env_bw = _os.environ.get(PEAK_HBM_GBPS_ENV)
    if env_tf or env_bw:
        try:
            return (
                float(env_tf) * 1e12 if env_tf else None,
                float(env_bw) * 1e9 if env_bw else None,
                "env",
            )
        except ValueError:
            pass
    if getattr(dev, "platform", None) != "tpu":
        return None, None, "unknown"
    kind = getattr(dev, "device_kind", "").lower()
    if "v5p" in kind:
        return (*CHIP_PEAKS["v5p"], "datasheet")
    if "v6" in kind:
        return (*CHIP_PEAKS["v6e"], "datasheet")
    if "lite" in kind or "v5e" in kind or "v5" in kind:
        return (*CHIP_PEAKS["v5e"], "datasheet")
    if "v4" in kind:
        return (*CHIP_PEAKS["v4"], "datasheet")
    return None, None, "unknown"


@dataclasses.dataclass(frozen=True)
class DispatchCostModel:
    """Per-device flop/byte cost of one engine dispatch, from the same
    shape arithmetic the memory plan uses (ISSUE 10).

    The engine calls the cost methods at every dispatch site with its
    host-known shapes (new tokens sampled, total KV context attended);
    the products divide by measured inter-dispatch wall time in
    runtime/metrics.py to yield MFU and HBM-bandwidth utilization.
    Deliberately an ESTIMATE: matmul flops use the 2·params convention
    (embedding lookups and norms are noise), attention uses 4·H·D per
    (query, kv) pair, and per-device sharing divides evenly across the
    mesh — replication factors (tq groups, norms) undercount a few
    percent, which is far inside the wall-time attribution error.
    """

    flops_per_token: float       # per device: matmul flops for 1 token
    attn_flops_per_kv: float     # per device: per (query, kv-token) pair
    weight_bytes: int            # per device: read once per dispatch step
    kv_bytes_per_token: int      # per device: one token's k+v row

    def decode_cost(self, new_tokens: int, kv_tokens: int,
                    steps: int = 1) -> tuple:
        """One decode dispatch advancing `new_tokens` lanes by `steps`
        fused steps, attending ~`kv_tokens` total context per step.
        Decode is HBM-bound: every weight byte streams once per step and
        the batch's whole KV window is gathered per step."""
        flops = steps * kv_tokens * self.attn_flops_per_kv \
            + new_tokens * self.flops_per_token
        bytes_ = steps * (self.weight_bytes
                          + kv_tokens * self.kv_bytes_per_token) \
            + new_tokens * self.kv_bytes_per_token
        return flops, bytes_

    def prefill_cost(self, chunk_tokens: int, start_tokens: int) -> tuple:
        """One prefill chunk of `chunk_tokens` starting at position
        `start_tokens`: causal attention pairs = chunk·start + chunk²/2;
        KV reads cover the materialized window once, writes the chunk."""
        pairs = chunk_tokens * start_tokens + chunk_tokens * chunk_tokens / 2
        flops = (chunk_tokens * self.flops_per_token
                 + pairs * self.attn_flops_per_kv)
        bytes_ = (self.weight_bytes
                  + (start_tokens + chunk_tokens) * self.kv_bytes_per_token
                  + chunk_tokens * self.kv_bytes_per_token)
        return flops, bytes_

    def verify_cost(self, query_tokens: int, kv_tokens: int,
                    attn_pairs: Optional[float] = None) -> tuple:
        """One speculative verify dispatch scoring `query_tokens` total
        candidate positions (sum over lanes of cand+1) against
        `kv_tokens` of context.  `attn_pairs` is the (query, kv-token)
        pair count — each of a lane's K+1 queries attends that lane's
        whole context, so pairs ~= kv_tokens x per-lane query width, NOT
        kv_tokens (the decode convention); callers pass it, the
        query_tokens fallback covers width-1 degenerate calls.  Bytes
        stay kv_tokens-based: the kernel streams each KV page once per
        lane regardless of query width."""
        if attn_pairs is None:
            attn_pairs = float(kv_tokens)
        flops = (query_tokens * self.flops_per_token
                 + attn_pairs * self.attn_flops_per_kv)
        bytes_ = (self.weight_bytes + kv_tokens * self.kv_bytes_per_token
                  + query_tokens * self.kv_bytes_per_token)
        return flops, bytes_


def dispatch_cost_model(
    cfg: ModelConfig,
    *,
    n_devices: int = 1,
    weight_bytes_total: Optional[int] = None,
    kv_dtype_bytes: int = 2,
    kv_replication: int = 1,
) -> DispatchCostModel:
    """Build the per-device dispatch cost model for an engine.

    `weight_bytes_total` is the engine's ACTUAL materialized parameter
    bytes when known (models/quant.param_bytes — exact for int8 trees);
    falls back to the planner's bf16 arithmetic.  `kv_replication` is the
    tq factor (grouped GQA replicates each kv head across its tq group,
    so per-device KV traffic does not shrink by the full device count).
    """
    if weight_bytes_total is None:
        weight_bytes_total = weight_bytes_per_device(cfg, tp=1)
    wb = _bytes(cfg.dtype)
    # params from the unsharded bf16 arithmetic (stable vs quantization)
    params_total = weight_bytes_per_device(cfg, tp=1) / wb
    n = max(1, n_devices)
    kv_row = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim \
        * kv_dtype_bytes
    return DispatchCostModel(
        flops_per_token=2.0 * params_total / n,
        attn_flops_per_kv=4.0 * cfg.num_layers * cfg.num_heads
        * cfg.head_dim / n,
        weight_bytes=int(weight_bytes_total // n),
        kv_bytes_per_token=int(kv_row * max(1, kv_replication) // n),
    )


def plan_for_serving(scfg, hbm_bytes: Optional[int] = None,
                     chip: str = "v5e",
                     model_cfg: Optional[ModelConfig] = None) -> MemoryPlan:
    """Plan from a ServingConfig (server/config.py).

    `model_cfg` overrides the registry lookup — the server passes the model
    it actually loaded (checkpoint / tiny configs differ from model_name).
    """
    if model_cfg is None:
        from ..models.config import get_config

        model_cfg = get_config(scfg.model_name)
    # resolve (tp, tq) the way the server will build the mesh — ulysses/pp
    # configs keep the plain axis and fall back to full kv replication,
    # and the plan must charge for THAT, not the grouped layout
    from ..parallel.mesh import resolve_tensor_axes

    tpk, tq = resolve_tensor_axes(
        scfg.tp_size, model_cfg.num_kv_heads,
        cp_strategy=getattr(scfg, "cp_strategy", "ring"),
        sp=scfg.sp_size, pp=scfg.pp_size,
    )
    kv_shard = tpk if (tq > 1 or model_cfg.num_kv_heads % tpk == 0) else 1
    return plan_memory(
        model_cfg,
        tp=scfg.tp_size, sp=scfg.sp_size, pp=scfg.pp_size, ep=scfg.ep_size,
        num_pages=scfg.num_pages, page_size=scfg.page_size,
        max_pages_per_seq=scfg.max_pages_per_seq, max_batch=scfg.max_batch,
        prefill_bucket=max(scfg.prefill_buckets),
        quantize=scfg.quantize,
        kv_dtype=getattr(scfg, "kv_quantize", "") or "bfloat16",
        hbm_bytes=hbm_bytes, chip=chip, kv_shard=kv_shard,
        # host-RAM tier budget (not HBM): stated in the plan so capacity
        # reviews see the full footprint of a tiered deployment
        kv_host_tier_bytes=getattr(scfg, "kv_host_tier_mb", 0) * MiB,
    )


# ---------------------------------------------------------------------------
# Live HBM accounting (ISSUE 18, leg b): reconcile the boot-time plan
# against what the device actually holds, at step cadence.

HBM_WATERMARK_ENV = "KAFKA_TPU_HBM_WATERMARK"
HBM_POLL_ENV = "KAFKA_TPU_HBM_POLL_S"


def _watermark_frac() -> Optional[float]:
    """Headroom watermark as a fraction of the device byte limit.
    Explicitly set -> that fraction (clamped to [0, 1)).  Unset ->
    0.03 for device-sourced samples and DISABLED for plan-synthesized
    ones: a barely-fitting plan on CPU smoke would otherwise hold an
    hbm_pressure anomaly forever on numbers that are a prediction, not
    a measurement."""
    raw = __import__("os").environ.get(HBM_WATERMARK_ENV)
    if raw is None or raw == "":
        return None
    try:
        return min(0.99, max(0.0, float(raw)))
    except ValueError:
        return None


class MemoryMonitor:
    """Per-engine live HBM gauge set (engine-thread single-writer).

    ``poll()`` reads every device's ``memory_stats()`` (throttled to
    ``KAFKA_TPU_HBM_POLL_S``, default 1s — one host RPC per device,
    never on the dispatch hot path more than that) and publishes one
    immutable section dict; readers (``/metrics``, ``/admin/signals``,
    the flight recorder's ``hbm_pressure`` detector) grab the latest
    reference torn-free.

    Devices without ``memory_stats`` (CPU smoke) synthesize the sample
    from the :class:`MemoryPlan` itself (``source: "plan"``,
    ``plan_skew`` pinned at 1.0) so every consumer downstream — the
    gauges, the signals section, the ladder input — exercises the same
    code path the TPU runs.
    """

    def __init__(self, devices, plan: Optional[MemoryPlan] = None,
                 poll_s: Optional[float] = None):
        import os as _os
        self.devices = list(devices)
        self.plan = plan
        if poll_s is None:
            try:
                poll_s = float(_os.environ.get(HBM_POLL_ENV, "1.0"))
            except ValueError:
                poll_s = 1.0
        self.poll_s = max(0.0, poll_s)
        explicit = _watermark_frac()
        self.watermark_frac = explicit
        self._watermark_explicit = explicit is not None
        self._last_poll_t: Optional[float] = None
        self._last: Optional[Dict[str, object]] = None
        self.polls = 0

    # -- sampling --------------------------------------------------------

    def poll(self, now: Optional[float] = None,
             force: bool = False) -> Optional[Dict[str, object]]:
        """Refresh the sample when the throttle allows; returns the
        current section either way (None before the first poll)."""
        import time as _time
        now = _time.monotonic() if now is None else now
        if (not force and self._last_poll_t is not None
                and now - self._last_poll_t < self.poll_s):
            return self._last
        self._last_poll_t = now
        self._last = self._sample()
        self.polls += 1
        return self._last

    def _sample(self) -> Dict[str, object]:
        per_dev = []
        for d in self.devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats or not stats.get("bytes_limit"):
                continue
            per_dev.append({
                "device": str(getattr(d, "id", len(per_dev))),
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "bytes_peak": int(stats.get(
                    "peak_bytes_in_use", stats.get("bytes_in_use", 0))),
                "bytes_limit": int(stats["bytes_limit"]),
            })
        plan = self.plan
        if per_dev:
            # worst device bounds the fleet: the plan is per-device
            in_use = max(d["bytes_in_use"] for d in per_dev)
            peak = max(d["bytes_peak"] for d in per_dev)
            limit = min(d["bytes_limit"] for d in per_dev)
            source = "device"
        elif plan is not None:
            in_use = plan.total_bytes
            peak = plan.total_bytes
            limit = plan.usable_bytes
            source = "plan"
        else:
            return {
                "source": "none", "hbm_bytes_in_use": 0,
                "hbm_bytes_peak": 0, "hbm_bytes_limit": 0,
                "hbm_headroom_bytes": 0, "hbm_plan_skew": 0.0,
                "hbm_pressure": 0, "hbm_component_bytes": {},
                "devices": [],
            }
        headroom = limit - in_use
        skew = (in_use / plan.total_bytes
                if plan is not None and plan.total_bytes else 0.0)
        wm = self.watermark_frac
        if wm is None:
            wm = 0.03 if source == "device" else None
        pressure = (wm is not None and limit > 0
                    and headroom < wm * limit)
        return {
            "source": source,
            "hbm_bytes_in_use": int(in_use),
            "hbm_bytes_peak": int(peak),
            "hbm_bytes_limit": int(limit),
            "hbm_headroom_bytes": int(headroom),
            "hbm_plan_skew": round(skew, 4),
            "hbm_pressure": 1 if pressure else 0,
            "hbm_component_bytes": self._attribution(in_use),
            "devices": per_dev,
        }

    def _attribution(self, in_use: int) -> Dict[str, int]:
        """Measured bytes reconciled against the plan's line items:
        each planned component at its planned charge, with the
        residual (gather staging, XLA scratch, fragmentation — real
        allocations the plan folds into reserve_frac) surfaced as
        ``unattributed``.  A strongly negative residual means the plan
        OVER-charges (plan_skew < 1): components larger than life."""
        plan = self.plan
        if plan is None:
            return {}
        comp = {
            "weights": plan.weight_bytes,
            "kv_pool": plan.kv_pool_bytes,
            "activations": plan.activation_bytes,
            "grammar_tables": plan.grammar_table_bytes,
        }
        comp["unattributed"] = int(in_use) - plan.total_bytes
        return comp

    # -- export ----------------------------------------------------------

    def section(self) -> Optional[Dict[str, object]]:
        """Latest sample (the ``memory`` metrics/signals section; keys
        registered as MEMORY_METRIC_KEYS in metrics.py)."""
        return self._last

    def pressure(self) -> bool:
        s = self._last
        return bool(s and s.get("hbm_pressure"))

    def headroom_frac(self) -> Optional[float]:
        """Headroom as a fraction of the limit (autoscaler sizing input:
        size against MEASURED headroom, not planned)."""
        s = self._last
        if not s or not s.get("hbm_bytes_limit"):
            return None
        return s["hbm_headroom_bytes"] / s["hbm_bytes_limit"]
