"""Structured logging: JSON lines correlated with the active trace.

``KAFKA_TPU_LOG_FORMAT=json`` switches every log record to one JSON
object per line, stamped with ``trace_id``/``span_id`` (from the ambient
tracing context, when the emitting code runs inside a traced request) and
``thread_id``/``thread``/``pid`` — the correlation keys that let an
operator grep a request's full story across the serving process AND its
sandbox subprocesses (which inherit the env knob through
``tracing.subprocess_env``).

Explicit ``extra={"trace_id": ...}`` fields on a record win over the
ambient context — the slow-request log uses this, since it fires from the
HTTP layer after the request's context is torn down.  Any other JSON-safe
``extra`` fields ride along verbatim (the slow log attaches its full span
breakdown this way).
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Optional

ENV_FORMAT = "KAFKA_TPU_LOG_FORMAT"

# attributes every LogRecord carries; anything else came in via extra=
_STANDARD = frozenset(
    logging.LogRecord("", 0, "", 0, "", (), None).__dict__
) | {"message", "asctime", "taskName"}


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload: dict = {
            "ts": round(record.created, 6),
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            ) + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "thread_id": record.thread,
            "thread": record.threadName,
            "pid": record.process,
        }
        # ambient trace correlation (imported lazily: logging must work
        # during interpreter teardown and partial imports)
        try:
            from . import tracing

            ctx = tracing.current()
            if ctx is not None:
                payload["trace_id"] = ctx.trace_id
                payload["span_id"] = ctx.span_id
        except Exception:
            pass
        for key, value in record.__dict__.items():
            if key in _STANDARD or key.startswith("_"):
                continue
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                value = repr(value)
            payload[key] = value
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload, separators=(",", ":"), default=str)


def setup_logging(
    fmt: Optional[str] = None, level: int = logging.INFO
) -> None:
    """Install the process-wide log format (server + sandbox entrypoints).

    ``fmt`` beats the env; "json" installs :class:`JsonFormatter` on the
    root handler, anything else keeps stdlib basicConfig text.  Idempotent:
    re-running swaps the formatter rather than stacking handlers.
    """
    fmt = (fmt or os.environ.get(ENV_FORMAT, "text")).lower()
    root = logging.getLogger()
    if not root.handlers:
        logging.basicConfig(level=level)
    root.setLevel(level)
    if fmt == "json":
        formatter: logging.Formatter = JsonFormatter()
    else:
        formatter = logging.Formatter(
            "%(levelname)s:%(name)s:%(message)s"
        )
    for handler in root.handlers:
        handler.setFormatter(formatter)


def log_extra(**fields: Any) -> dict:
    """Convenience: ``logger.info(msg, extra=log_extra(trace_id=...))``."""
    return fields
