"""Prometheus text exposition for the /metrics snapshot.

``GET /metrics?format=prometheus`` renders the same snapshot the JSON
endpoint serves (one source of truth — the engine's EngineMetrics, plus
sandbox-supervision and tracing counters merged by server/app.py) in the
classic text format (version 0.0.4): ``# TYPE`` lines, stable metric
names, label escaping per the spec.  Percentile families render as
summaries with ``quantile`` labels (p50 → 0.5 etc.).

The renderer tolerates both snapshot shapes — a single engine's and the
DP aggregate's (which lacks the TTFT breakdown and adds the
replica_supervisor section) — by keying every family off ``.get``.
A tier-1 test parses the output with a minimal format checker (no
duplicate series, every family typed, values float-parseable) so the
endpoint stays scrapeable.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

_QUANTILE = {"p50": "0.5", "p90": "0.9", "p99": "0.99"}


def _escape(value: str) -> str:
    """Label-value escaping per the exposition format spec."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(value: Any) -> str:
    try:
        f = float(value)
    except (TypeError, ValueError):
        return "0"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Writer:
    def __init__(self) -> None:
        self.lines: List[str] = []
        self._typed: set = set()

    def family(self, name: str, kind: str, help_text: str) -> None:
        if name in self._typed:
            return
        self._typed.add(name)
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {kind}")

    def sample(
        self, name: str, value: Any,
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        if labels:
            rendered = ",".join(
                f'{k}="{_escape(v)}"' for k, v in labels.items()
            )
            self.lines.append(f"{name}{{{rendered}}} {_fmt(value)}")
        else:
            self.lines.append(f"{name} {_fmt(value)}")

    def summary(
        self, name: str, quantiles: Dict[str, Any], help_text: str,
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.family(name, "summary", help_text)
        for p, q in _QUANTILE.items():
            if p in quantiles:
                self.sample(name, quantiles[p],
                            {**(labels or {}), "quantile": q})

    def histogram_family(
        self, name: str, help_text: str,
        rows: List[tuple],
    ) -> None:
        """One histogram family from StreamingHistogram snapshots
        (ISSUE 10): true ``_bucket`` series with CUMULATIVE counts per
        ``le`` bound (monotone by construction — the wire snapshot holds
        non-negative per-bucket counts), a ``+Inf`` bucket equal to
        ``_count``, and ``_sum``.  `rows` is [(labels, hist_snapshot)] —
        all bucket series render before the sums/counts so each sample
        NAME stays one contiguous group (exposition single-group rule,
        enforced by the in-tree parser)."""
        self.family(name, "histogram", help_text)
        for labels, h in rows:
            cum = 0
            for le, c in zip(h["le"], h["counts"]):
                cum += c
                self.sample(f"{name}_bucket", cum,
                            {**labels, "le": _fmt(le)})
            self.sample(f"{name}_bucket", sum(h["counts"]),
                        {**labels, "le": "+Inf"})
        for labels, h in rows:
            self.sample(f"{name}_sum", h["sum"], labels or None)
        for labels, h in rows:
            self.sample(f"{name}_count", sum(h["counts"]), labels or None)

    def render(self) -> str:
        return "\n".join(self.lines) + "\n"


# histogram snapshot name -> (family, extra labels).  The three TTFT
# phases share ONE family distinguished by the phase label, mirroring the
# JSON breakdown section.
_HISTOGRAM_FAMILIES = (
    ("ttft_ms", "kafka_tpu_ttft_milliseconds",
     "Time to first token.", {}),
    ("tpot_ms", "kafka_tpu_tpot_milliseconds",
     "Time per output token.", {}),
    ("ttft_queue_ms", "kafka_tpu_ttft_phase_milliseconds",
     "TTFT decomposition by phase.", {"phase": "queue_wait"}),
    ("ttft_prefill_ms", "kafka_tpu_ttft_phase_milliseconds",
     "TTFT decomposition by phase.", {"phase": "prefill"}),
    ("ttft_fetch_ms", "kafka_tpu_ttft_phase_milliseconds",
     "TTFT decomposition by phase.", {"phase": "first_fetch"}),
    ("burst_tokens", "kafka_tpu_emission_burst_tokens",
     "Tokens arriving together per emission burst.", {}),
    ("burst_gap_ms", "kafka_tpu_emission_burst_gap_milliseconds",
     "Gap between emission bursts.", {}),
)


def _render_histograms(w: "_Writer", snap: Dict[str, Any]) -> None:
    """The latency/size histogram families: aggregate series plus one
    replica-labeled series per DP replica (contiguous per family)."""
    hists = snap.get("histograms") or {}
    if not hists:
        return
    replica_hists = [
        (idx, rs.get("histograms") or {})
        for idx, rs in enumerate(snap.get("replicas") or [])
        if rs.get("histograms")
    ]
    by_family: Dict[str, List[tuple]] = {}
    help_by_family: Dict[str, str] = {}
    for key, family, help_text, labels in _HISTOGRAM_FAMILIES:
        if key not in hists:
            continue
        help_by_family[family] = help_text
        rows = by_family.setdefault(family, [])
        rows.append((dict(labels), hists[key]))
        for idx, rh in replica_hists:
            if key in rh:
                rows.append(({**labels, "replica": idx}, rh[key]))
    for family, rows in by_family.items():
        w.histogram_family(family, help_by_family[family], rows)


def render_prometheus(snap: Dict[str, Any]) -> str:
    w = _Writer()

    w.family("kafka_tpu_uptime_seconds", "gauge", "Engine uptime.")
    w.sample("kafka_tpu_uptime_seconds", snap.get("uptime_s", 0))

    requests = snap.get("requests") or {}
    if requests:
        w.family("kafka_tpu_requests_total", "counter",
                 "Requests by terminal state (submitted counts ingress).")
        for state, v in requests.items():
            w.sample("kafka_tpu_requests_total", v, {"state": state})

    queue = snap.get("queue") or {}
    if queue:
        w.family("kafka_tpu_queue_depth", "gauge",
                 "Engine waiting-queue depth (last scheduler iteration).")
        w.sample("kafka_tpu_queue_depth", queue.get("depth", 0))
        w.family("kafka_tpu_queue_depth_peak", "gauge",
                 "Peak waiting-queue depth since the previous snapshot "
                 "(each scrape re-arms the high-water mark).")
        w.sample("kafka_tpu_queue_depth_peak", queue.get("peak", 0))
        if "trend_per_s" in queue:
            w.family("kafka_tpu_queue_depth_trend_per_second", "gauge",
                     "Queue-depth slope over the last minute (>0 = "
                     "growing; an autoscaler scale-up signal).")
            w.sample("kafka_tpu_queue_depth_trend_per_second",
                     queue["trend_per_s"])

    tokens = snap.get("tokens") or {}
    if tokens:
        w.family("kafka_tpu_tokens_total", "counter",
                 "Token counters by kind.")
        # fetch_pipeline_wasted was exported as kind="speculative_wasted"
        # before real speculative decoding existed (renamed PR 5; the
        # JSON endpoint's deprecated aliases were removed one release
        # later — README "Metrics rename")
        for kind in ("prompt", "generated", "fetch_pipeline_wasted"):
            if kind in tokens:
                w.sample("kafka_tpu_tokens_total", tokens[kind],
                         {"kind": kind})
        w.family("kafka_tpu_tokens_generated_per_second", "gauge",
                 "Decode throughput over uptime.")
        w.sample("kafka_tpu_tokens_generated_per_second",
                 tokens.get("generated_per_s", 0))

    # Latency/size distributions: TRUE histogram families (_bucket with
    # le labels, _sum, _count) from the streaming-histogram snapshots —
    # cumulative since boot, mergeable in PromQL, per replica and
    # aggregated (ISSUE 10; replaces the old summary-quantile rendering).
    # When the snapshot predates histograms (stale client), fall back to
    # the summary form so the endpoint never goes dark.
    if snap.get("histograms"):
        _render_histograms(w, snap)
    else:
        if "ttft_ms" in snap:
            w.summary("kafka_tpu_ttft_milliseconds", snap["ttft_ms"],
                      "Time to first token (percentiles).")
        for phase, q in (snap.get("ttft_breakdown_ms") or {}).items():
            w.summary("kafka_tpu_ttft_phase_milliseconds", q,
                      "TTFT decomposition by phase.",
                      labels={"phase": phase})
        if "tpot_ms" in snap:
            w.summary("kafka_tpu_tpot_milliseconds", snap["tpot_ms"],
                      "Time per output token (percentiles).")

    decode = snap.get("decode") or {}
    if decode:
        w.family("kafka_tpu_decode_steps_total", "counter",
                 "Decode steps dispatched (fused steps count k).")
        w.sample("kafka_tpu_decode_steps_total", decode.get("steps", 0))
        w.family("kafka_tpu_batch_occupancy", "gauge",
                 "Mean busy decode slots per step.")
        w.sample("kafka_tpu_batch_occupancy",
                 decode.get("batch_occupancy", 0))

    if not snap.get("histograms"):
        emission = snap.get("emission") or {}
        if "burst_tokens" in emission:
            w.summary("kafka_tpu_emission_burst_tokens",
                      emission["burst_tokens"],
                      "Tokens arriving together per emission burst.")
        if "burst_gap_ms" in emission:
            w.summary("kafka_tpu_emission_burst_gap_milliseconds",
                      emission["burst_gap_ms"],
                      "Gap between emission bursts.")

    # SLO / goodput (runtime/metrics.SLO_METRIC_KEYS — the registry a
    # static test enforces in both files).  The autoscaler's primary
    # inputs: attainment per window, goodput vs raw throughput.
    slo = snap.get("slo") or {}
    if slo:
        w.family("kafka_tpu_slo_requests_total", "counter",
                 "Requests by SLO verdict at finalize (timeouts, engine "
                 "failures and 429 rejections count as missed; client "
                 "cancels are excluded).")
        for key, result in (("slo_met_requests", "met"),
                            ("slo_missed_requests", "missed")):
            if key in slo:
                w.sample("kafka_tpu_slo_requests_total", slo[key],
                         {"result": result})
        w.family("kafka_tpu_slo_violations_total", "counter",
                 "Missed-SLO attributions by violated target.")
        for key, kind in (("slo_ttft_violations", "ttft"),
                          ("slo_tpot_violations", "tpot")):
            if key in slo:
                w.sample("kafka_tpu_slo_violations_total", slo[key],
                         {"kind": kind})
        w.family("kafka_tpu_slo_target_milliseconds", "gauge",
                 "Configured SLO targets (0 = target disabled).")
        for key, kind in (("slo_ttft_target_ms", "ttft"),
                          ("slo_tpot_target_ms", "tpot")):
            if key in slo:
                w.sample("kafka_tpu_slo_target_milliseconds", slo[key],
                         {"kind": kind})
        w.family("kafka_tpu_slo_attainment", "gauge",
                 "Fraction of finalized requests meeting every SLO "
                 "target, by window (1.0 when the window saw none).")
        for key, window in (("slo_attainment", "total"),
                            ("slo_attainment_1m", "1m"),
                            ("slo_attainment_5m", "5m")):
            if key in slo:
                w.sample("kafka_tpu_slo_attainment", slo[key],
                         {"window": window})
        if "goodput_tokens" in slo:
            w.family("kafka_tpu_goodput_tokens_total", "counter",
                     "Tokens generated by SLO-met requests.")
            w.sample("kafka_tpu_goodput_tokens_total",
                     slo["goodput_tokens"])
        w.family("kafka_tpu_goodput_tokens_per_second", "gauge",
                 "Goodput rate by window (SLO-met tokens only).")
        for key, window in (("goodput_tok_s", "total"),
                            ("goodput_tok_s_1m", "1m")):
            if key in slo:
                w.sample("kafka_tpu_goodput_tokens_per_second", slo[key],
                         {"window": window})
        if "goodput_frac" in slo:
            w.family("kafka_tpu_goodput_fraction", "gauge",
                     "Goodput tokens / raw generated tokens.")
            w.sample("kafka_tpu_goodput_fraction", slo["goodput_frac"])

    # Device-utilization estimator (runtime/metrics.UTILIZATION_METRIC_
    # KEYS), per dispatch kind; counters enable PromQL rate()-based MFU,
    # the gauges are the ready-made since-boot and 1m ratios.  Per-replica
    # ratio gauges ride as labeled series next to the aggregate.
    util = snap.get("utilization") or {}
    kinds = [k for k in ("prefill", "decode", "verify") if k in util]
    if kinds:
        replica_utils = [
            (idx, rs.get("utilization") or {})
            for idx, rs in enumerate(snap.get("replicas") or [])
            if rs.get("utilization")
        ]
        w.family("kafka_tpu_dispatches_total", "counter",
                 "Device dispatches by kind.")
        for k in kinds:
            w.sample("kafka_tpu_dispatches_total",
                     util[k].get("dispatches", 0), {"kind": k})
        w.family("kafka_tpu_dispatch_tokens_total", "counter",
                 "Tokens processed by dispatch kind.")
        for k in kinds:
            w.sample("kafka_tpu_dispatch_tokens_total",
                     util[k].get("tokens", 0), {"kind": k})
        w.family("kafka_tpu_device_flops_total", "counter",
                 "Modeled device FLOPs by dispatch kind (planner cost "
                 "model).")
        for k in kinds:
            w.sample("kafka_tpu_device_flops_total",
                     util[k].get("flops", 0), {"kind": k})
        w.family("kafka_tpu_device_hbm_bytes_total", "counter",
                 "Modeled HBM bytes moved by dispatch kind.")
        for k in kinds:
            w.sample("kafka_tpu_device_hbm_bytes_total",
                     util[k].get("hbm_bytes", 0), {"kind": k})
        w.family("kafka_tpu_dispatch_busy_seconds_total", "counter",
                 "Wall time attributed to dispatch execution by kind.")
        for k in kinds:
            w.sample("kafka_tpu_dispatch_busy_seconds_total",
                     util[k].get("busy_s", 0), {"kind": k})
        w.family("kafka_tpu_mfu", "gauge",
                 "Model FLOPs utilization vs the chip roofline, by "
                 "dispatch kind and window (0 when no roofline known).")
        for k in kinds:
            for key, window in (("mfu", "total"), ("mfu_1m", "1m")):
                w.sample("kafka_tpu_mfu", util[k].get(key, 0),
                         {"kind": k, "window": window})
        for idx, ru in replica_utils:
            for k in kinds:
                if k in ru:
                    for key, window in (("mfu", "total"),
                                        ("mfu_1m", "1m")):
                        w.sample("kafka_tpu_mfu", ru[k].get(key, 0),
                                 {"replica": idx, "kind": k,
                                  "window": window})
        w.family("kafka_tpu_hbm_bandwidth_utilization", "gauge",
                 "HBM bandwidth utilization vs the chip roofline, by "
                 "dispatch kind and window.")
        for k in kinds:
            for key, window in (("hbm_bw_util", "total"),
                                ("hbm_bw_util_1m", "1m")):
                w.sample("kafka_tpu_hbm_bandwidth_utilization",
                         util[k].get(key, 0),
                         {"kind": k, "window": window})
        for idx, ru in replica_utils:
            for k in kinds:
                if k in ru:
                    for key, window in (("hbm_bw_util", "total"),
                                        ("hbm_bw_util_1m", "1m")):
                        w.sample("kafka_tpu_hbm_bandwidth_utilization",
                                 ru[k].get(key, 0),
                                 {"replica": idx, "kind": k,
                                  "window": window})
        # Measured dispatch timing + model skew (ISSUE 11, the flight
        # recorder's fetch-maturation derivation): counters for PromQL
        # rate()-based skew, plus the ready-made since-boot ratio gauge.
        w.family("kafka_tpu_measured_dispatches_total", "counter",
                 "Dispatches with a measured device-time sample by kind.")
        for k in kinds:
            w.sample("kafka_tpu_measured_dispatches_total",
                     util[k].get("measured_dispatches", 0), {"kind": k})
        w.family("kafka_tpu_dispatch_measured_seconds_total", "counter",
                 "Measured device execution time by dispatch kind "
                 "(fetch-maturation timing).")
        for k in kinds:
            w.sample("kafka_tpu_dispatch_measured_seconds_total",
                     util[k].get("measured_busy_s", 0), {"kind": k})
        w.family("kafka_tpu_dispatch_modeled_seconds_total", "counter",
                 "Modeled roofline execution time for the SAME measured "
                 "dispatches, by kind.")
        for k in kinds:
            w.sample("kafka_tpu_dispatch_modeled_seconds_total",
                     util[k].get("modeled_busy_s", 0), {"kind": k})
        w.family("kafka_tpu_dispatch_model_skew", "gauge",
                 "Measured / modeled dispatch time by kind (>1 = the "
                 "device runs slower than the cost model assumes, so the "
                 "modeled MFU/HBM-BW figures read high by this factor; "
                 "0 = no samples yet).")
        for k in kinds:
            w.sample("kafka_tpu_dispatch_model_skew",
                     util[k].get("model_skew", 0), {"kind": k})
        # Profiler-sampled kernel truth (ISSUE 18, runtime/
        # kernel_profiler.py): TRUE device kernel seconds from sampled
        # jax.profiler traces vs the modeled seconds of those same
        # sampled steps — the chip-truth calibration model_skew is read
        # against (keys kernel_samples / kernel_busy_s / kernel_skew).
        w.family("kafka_tpu_kernel_samples_total", "counter",
                 "Profiler trace samples attributed to this dispatch "
                 "kind (KAFKA_TPU_PROFILE_SAMPLE).")
        for k in kinds:
            w.sample("kafka_tpu_kernel_samples_total",
                     util[k].get("kernel_samples", 0), {"kind": k})
        w.family("kafka_tpu_kernel_seconds_total", "counter",
                 "True device kernel time by dispatch kind, from "
                 "sampled profiler traces.")
        for k in kinds:
            w.sample("kafka_tpu_kernel_seconds_total",
                     util[k].get("kernel_busy_s", 0), {"kind": k})
        w.family("kafka_tpu_kernel_skew", "gauge",
                 "Sampled device kernel time / modeled roofline time "
                 "for the same steps, by kind (0 = no samples yet).")
        for k in kinds:
            w.sample("kafka_tpu_kernel_skew",
                     util[k].get("kernel_skew", 0), {"kind": k})
        if util.get("peak_tflops"):
            w.family("kafka_tpu_device_peak_teraflops", "gauge",
                     "Roofline peak FLOP/s per chip (datasheet or env "
                     "override), in TFLOP/s.")
            w.sample("kafka_tpu_device_peak_teraflops",
                     util["peak_tflops"])
        if util.get("peak_hbm_gbps"):
            w.family("kafka_tpu_device_peak_hbm_gigabytes_per_second",
                     "gauge",
                     "Roofline peak HBM bandwidth per chip, in GB/s.")
            w.sample("kafka_tpu_device_peak_hbm_gigabytes_per_second",
                     util["peak_hbm_gbps"])

    # constrained decoding (runtime/metrics.CONSTRAINED_METRIC_KEYS — the
    # registry a static test enforces in both files)
    con = dict(snap.get("constrained") or {})
    if "constrained_roundtrips" not in con and "constrained_roundtrips" in snap:
        con["constrained_roundtrips"] = snap["constrained_roundtrips"]
    if "constrained_roundtrips" in con:
        w.family("kafka_tpu_constrained_roundtrips_total", "counter",
                 "Constrained choice points that awaited a device fetch.")
        w.sample("kafka_tpu_constrained_roundtrips_total",
                 con["constrained_roundtrips"])
    if "constrained_mask_overtight" in con:
        w.family("kafka_tpu_constrained_overtight_total", "counter",
                 "Over-tight constrained mask rows degraded to "
                 "unconstrained sampling.")
        w.sample("kafka_tpu_constrained_overtight_total",
                 con["constrained_mask_overtight"])
    if "constrained_ondevice_tokens" in con:
        w.family("kafka_tpu_constrained_ondevice_tokens_total", "counter",
                 "Tokens emitted through the device-resident grammar FSM "
                 "(zero-roundtrip constrained decoding).")
        w.sample("kafka_tpu_constrained_ondevice_tokens_total",
                 con["constrained_ondevice_tokens"])
    if "constrained_compile_pending" in con:
        w.family("kafka_tpu_constrained_compile_pending", "gauge",
                 "Grammar compiles queued/running on the background "
                 "deferred-compile worker (requests use the host-mask "
                 "path until their table lands).")
        w.sample("kafka_tpu_constrained_compile_pending",
                 con["constrained_compile_pending"])

    spec = snap.get("speculation") or {}
    if spec:
        # speculative decoding (draft-free n-gram + batched verify).
        # Family names mirror runtime/metrics.SPECULATION_METRIC_KEYS —
        # the registry a static test enforces in both files.
        w.family("kafka_tpu_speculation_tokens_total", "counter",
                 "Speculative candidate tokens by outcome.")
        for key, kind in (
            ("speculation_proposed_tokens", "proposed"),
            ("speculation_accepted_tokens", "accepted"),
            ("speculation_rejected_tokens", "rejected"),
        ):
            if key in spec:
                w.sample("kafka_tpu_speculation_tokens_total", spec[key],
                         {"kind": kind})
        if "speculation_verify_steps" in spec:
            w.family("kafka_tpu_speculation_verify_steps_total", "counter",
                     "Speculative verify dispatches.")
            w.sample("kafka_tpu_speculation_verify_steps_total",
                     spec["speculation_verify_steps"])
        if "speculation_acceptance_rate" in spec:
            w.family("kafka_tpu_speculation_acceptance_rate", "gauge",
                     "Accepted / (accepted + rejected) candidate tokens.")
            w.sample("kafka_tpu_speculation_acceptance_rate",
                     spec["speculation_acceptance_rate"])
        if "speculation_accepted_per_step" in spec:
            w.family("kafka_tpu_speculation_accepted_per_step", "gauge",
                     "Mean accepted candidates per verify dispatch.")
            w.sample("kafka_tpu_speculation_accepted_per_step",
                     spec["speculation_accepted_per_step"])

    engine = snap.get("engine") or {}
    if engine:
        w.family("kafka_tpu_engine_active", "gauge",
                 "Requests holding a decode slot.")
        w.sample("kafka_tpu_engine_active", engine.get("active", 0))
        w.family("kafka_tpu_engine_waiting", "gauge",
                 "Requests in the waiting queue.")
        w.sample("kafka_tpu_engine_waiting", engine.get("waiting", 0))
        w.family("kafka_tpu_kv_pages", "gauge",
                 "KV pool pages by state.")
        for key, label in (("pages_total", "total"),
                           ("pages_free", "free"),
                           ("pages_in_use", "in_use")):
            if key in engine:
                w.sample("kafka_tpu_kv_pages", engine[key],
                         {"state": label})
        if "rtt_est_ms" in engine:
            w.family("kafka_tpu_device_rtt_milliseconds", "gauge",
                     "Estimated device-to-host fetch round trip.")
            w.sample("kafka_tpu_device_rtt_milliseconds",
                     engine["rtt_est_ms"])

    if "dp" in snap:
        w.family("kafka_tpu_dp_replicas", "gauge",
                 "Configured DP replica count.")
        w.sample("kafka_tpu_dp_replicas", snap["dp"])

    pc = snap.get("prefix_cache") or {}
    # DP aggregates sum per-replica prefix caches; export each replica's
    # cache as its own labeled series too (replica="<i>") so a dashboard
    # can see WHERE the radix trees are hot, while the unlabeled aggregate
    # series keeps existing dashboards working.  The exposition format
    # requires every sample of a family in ONE contiguous group, so the
    # aggregate and replica-labeled samples are emitted per family, not
    # per section.
    replica_pcs = [
        (idx, rs.get("prefix_cache") or {})
        for idx, rs in enumerate(snap.get("replicas") or [])
        if rs.get("prefix_cache")
    ]
    if pc:
        w.family("kafka_tpu_prefix_cache_entries", "gauge",
                 "Live prefix-cache entries (radix nodes; legacy name).")
        w.sample("kafka_tpu_prefix_cache_entries", pc.get("entries", 0))
    if "nodes" in pc or any("nodes" in r for _, r in replica_pcs):
        w.family("kafka_tpu_prefix_cache_nodes", "gauge",
                 "Radix-tree nodes (page-aligned token runs).")
        if "nodes" in pc:
            w.sample("kafka_tpu_prefix_cache_nodes", pc["nodes"])
        for idx, rpc in replica_pcs:
            if "nodes" in rpc:
                w.sample("kafka_tpu_prefix_cache_nodes", rpc["nodes"],
                         {"replica": idx})
    if "cached_pages" in pc or any("cached_pages" in r
                                   for _, r in replica_pcs):
        w.family("kafka_tpu_prefix_cache_pages", "gauge",
                 "KV pages the prefix cache currently retains.")
        if "cached_pages" in pc:
            w.sample("kafka_tpu_prefix_cache_pages", pc["cached_pages"])
        for idx, rpc in replica_pcs:
            if "cached_pages" in rpc:
                w.sample("kafka_tpu_prefix_cache_pages",
                         rpc["cached_pages"], {"replica": idx})
    if pc or replica_pcs:
        w.family("kafka_tpu_prefix_cache_total", "counter",
                 "Prefix-cache events by kind.")
        for kind in ("hits", "misses", "tokens_reused",
                     "cross_thread_hits", "host_tier_hits",
                     "shipped_hits", "object_tier_hits",
                     "evictions", "pages_evicted"):
            if kind in pc:
                w.sample("kafka_tpu_prefix_cache_total", pc[kind],
                         {"kind": kind})
        for idx, rpc in replica_pcs:
            for kind in ("hits", "misses", "tokens_reused",
                         "cross_thread_hits", "host_tier_hits",
                         "shipped_hits", "object_tier_hits",
                         "evictions", "pages_evicted"):
                if kind in rpc:
                    w.sample("kafka_tpu_prefix_cache_total", rpc[kind],
                             {"replica": idx, "kind": kind})
    if "host_nodes" in pc or "host_pages" in pc:
        w.family("kafka_tpu_prefix_cache_host_resident", "gauge",
                 "Radix runs currently demoted to the KV tier "
                 "(still matchable; promoted back on lookup).")
        for kind in ("host_nodes", "host_pages"):
            if kind in pc:
                w.sample("kafka_tpu_prefix_cache_host_resident",
                         pc[kind], {"kind": kind})

    # tiered KV cache (runtime/metrics.KV_TIER_METRIC_KEYS — the registry
    # a static test enforces in both files; tests/test_kv_tier.py)
    tier = snap.get("kv_tier") or {}
    if tier:
        w.family("kafka_tpu_kv_tier_bytes", "gauge",
                 "Tiered-KV occupancy and budget by tier.")
        for key, labels in (
            ("host_bytes", {"tier": "host", "kind": "used"}),
            ("host_budget_bytes", {"tier": "host", "kind": "budget"}),
            ("disk_bytes", {"tier": "disk", "kind": "used"}),
        ):
            if key in tier:
                w.sample("kafka_tpu_kv_tier_bytes", tier[key], labels)
        w.family("kafka_tpu_kv_tier_runs", "gauge",
                 "Demoted page runs resident per tier.")
        for key, label in (("host_runs", "host"), ("disk_runs", "disk")):
            if key in tier:
                w.sample("kafka_tpu_kv_tier_runs", tier[key],
                         {"tier": label})
        w.family("kafka_tpu_kv_tier_total", "counter",
                 "Tiered-KV events by kind.")
        for key in ("demotions", "demote_failures", "promotions",
                    "promote_failures", "host_evictions", "disk_spills",
                    "disk_loads"):
            if key in tier:
                w.sample("kafka_tpu_kv_tier_total", tier[key],
                         {"event": key})
        w.family("kafka_tpu_kv_tier_pages_total", "counter",
                 "Pages shipped between tiers by direction.")
        for key, label in (("pages_demoted", "demoted"),
                           ("pages_promoted", "promoted")):
            if key in tier:
                w.sample("kafka_tpu_kv_tier_pages_total", tier[key],
                         {"dir": label})
        w.family("kafka_tpu_kv_tier_bytes_total", "counter",
                 "Bytes shipped between tiers by direction.")
        for key, label in (("bytes_demoted", "demoted"),
                           ("bytes_promoted", "promoted")):
            if key in tier:
                w.sample("kafka_tpu_kv_tier_bytes_total", tier[key],
                         {"dir": label})

    # Object-store KV tier (runtime/metrics.OBJECT_TIER_METRIC_KEYS — the
    # registry tests/test_object_tier.py enforces in both files; present
    # only when KAFKA_TPU_KV_OBJECT_DIR mounts the shared store).
    obj = snap.get("object_tier") or {}
    if obj:
        w.family("kafka_tpu_object_tier_bytes", "gauge",
                 "Object-store occupancy: scope=store is the SHARED "
                 "store (report once per store when aggregating "
                 "scrapes); scope=owned is this replica's references.")
        for key, scope in (("store_bytes", "store"),
                           ("owned_bytes", "owned")):
            if key in obj:
                w.sample("kafka_tpu_object_tier_bytes", obj[key],
                         {"scope": scope})
        if "store_objects" in obj:
            w.family("kafka_tpu_object_tier_objects", "gauge",
                     "Run objects resident in the shared store.")
            w.sample("kafka_tpu_object_tier_objects",
                     obj["store_objects"])
        if "object_puts" in obj:
            w.family("kafka_tpu_object_tier_puts_total", "counter",
                     "Run payloads archived into the store.")
            w.sample("kafka_tpu_object_tier_puts_total",
                     obj["object_puts"])
        if "object_gets" in obj:
            w.family("kafka_tpu_object_tier_gets_total", "counter",
                     "Run payloads fetched from the store (wakes).")
            w.sample("kafka_tpu_object_tier_gets_total",
                     obj["object_gets"])
        w.family("kafka_tpu_object_tier_bytes_total", "counter",
                 "Object-store payload bytes moved by direction.")
        for key, label in (("object_bytes_put", "put"),
                           ("object_bytes_got", "get")):
            if key in obj:
                w.sample("kafka_tpu_object_tier_bytes_total", obj[key],
                         {"dir": label})
        w.family("kafka_tpu_object_tier_failures_total", "counter",
                 "Torn/failed store operations (put = archive degraded "
                 "to plain eviction; get = wake aborted, pages freed).")
        for key, op in (("object_put_failures", "put"),
                        ("object_get_failures", "get")):
            if key in obj:
                w.sample("kafka_tpu_object_tier_failures_total",
                         obj[key], {"op": op})
        if "dedupe_hits" in obj:
            w.family("kafka_tpu_object_tier_dedupe_hits_total", "counter",
                     "Puts whose content was already present (cross-host "
                     "prefix dedupe — only a reference was added).")
            w.sample("kafka_tpu_object_tier_dedupe_hits_total",
                     obj["dedupe_hits"])
        if "wake_threads" in obj:
            w.family("kafka_tpu_object_tier_wake_threads_total",
                     "counter",
                     "Dormant threads re-materialized from their sleep "
                     "manifests (cache_source=\"object_tier\").")
            w.sample("kafka_tpu_object_tier_wake_threads_total",
                     obj["wake_threads"])
        if "wake_tokens" in obj:
            w.family("kafka_tpu_object_tier_wake_tokens_total", "counter",
                     "Tokens re-materialized by sleep-manifest wakes "
                     "(prompt tokens NOT re-prefilled).")
            w.sample("kafka_tpu_object_tier_wake_tokens_total",
                     obj["wake_tokens"])
        if "manifests_written" in obj:
            w.family("kafka_tpu_object_tier_manifests_total", "counter",
                     "Per-thread sleep manifests written.")
            w.sample("kafka_tpu_object_tier_manifests_total",
                     obj["manifests_written"])
        if "objects_released" in obj:
            w.family("kafka_tpu_object_tier_released_total", "counter",
                     "Owner references dropped (budget eviction / thread "
                     "invalidation; the last reference deletes the "
                     "object).")
            w.sample("kafka_tpu_object_tier_released_total",
                     obj["objects_released"])
        # Store-guard families (ISSUE 17): retry/deadline/breaker/scrub
        # visibility for the resilience layer around the shared store.
        if "store_retries" in obj:
            w.family("kafka_tpu_object_store_retries_total", "counter",
                     "Store ops retried by the guard (idempotent "
                     "protocol ops, bounded exponential backoff).")
            w.sample("kafka_tpu_object_store_retries_total",
                     obj["store_retries"])
        if "store_timeouts" in obj:
            w.family("kafka_tpu_object_store_timeouts_total", "counter",
                     "Store op attempts that exceeded the per-op "
                     "deadline (KAFKA_TPU_KV_OBJECT_TIMEOUT_S).")
            w.sample("kafka_tpu_object_store_timeouts_total",
                     obj["store_timeouts"])
        if "store_breaker_opens" in obj:
            w.family("kafka_tpu_object_store_breaker_open_total",
                     "counter",
                     "Circuit-breaker open transitions (consecutive "
                     "store failures crossed the trip threshold).")
            w.sample("kafka_tpu_object_store_breaker_open_total",
                     obj["store_breaker_opens"])
        if "store_breaker_state" in obj:
            w.family("kafka_tpu_object_store_breaker_state", "gauge",
                     "Store circuit-breaker state: 0=closed, "
                     "1=half-open, 2=open (ops fast-fail).")
            w.sample("kafka_tpu_object_store_breaker_state",
                     obj["store_breaker_state"])
        if "store_probe_neg_cached" in obj:
            w.family("kafka_tpu_object_store_probe_neg_cached_total",
                     "counter",
                     "Manifest probes answered from the negative cache "
                     "while the store is unhealthy (zero store RTT on "
                     "the submit path).")
            w.sample("kafka_tpu_object_store_probe_neg_cached_total",
                     obj["store_probe_neg_cached"])
        if "store_scrub_repairs" in obj:
            w.family("kafka_tpu_object_store_scrub_repairs_total",
                     "counter",
                     "Crash-window orphans repaired by the scrubber "
                     "(ref-less objects, dangling refs, dead "
                     "manifests).")
            w.sample("kafka_tpu_object_store_scrub_repairs_total",
                     obj["store_scrub_repairs"])
        # Wake-prefetch families (ISSUE 19): object GETs started at
        # submit time so the store RTT overlaps queue wait.
        if "prefetch_hits" in obj:
            w.family("kafka_tpu_object_tier_prefetch_total", "counter",
                     "Wake-prefetch outcomes: hit = staged payload "
                     "consumed by admission (zero fetch RTT); wasted = "
                     "staged/fetched but dropped (cancel, budget "
                     "eviction, superseded).")
            w.sample("kafka_tpu_object_tier_prefetch_total",
                     obj["prefetch_hits"], {"outcome": "hit"})
            if "prefetch_wasted" in obj:
                w.sample("kafka_tpu_object_tier_prefetch_total",
                         obj["prefetch_wasted"], {"outcome": "wasted"})
        if "prefetch_bytes" in obj:
            w.family("kafka_tpu_object_tier_prefetch_bytes_total",
                     "counter",
                     "Run payload bytes staged by wake prefetch.")
            w.sample("kafka_tpu_object_tier_prefetch_bytes_total",
                     obj["prefetch_bytes"])
        if "prefetch_inflight" in obj:
            w.family("kafka_tpu_object_tier_prefetch_inflight", "gauge",
                     "Prefetch GETs scheduled but not yet resolved.")
            w.sample("kafka_tpu_object_tier_prefetch_inflight",
                     obj["prefetch_inflight"])

    # Disaggregated prefill/decode (runtime/metrics.DISAGG_METRIC_KEYS —
    # the registry a static test enforces in both files; present only
    # when KAFKA_TPU_DP_ROLES configures role pools).  Ship counters by
    # direction-less kind, the torn-copy failure counter the chaos
    # acceptance keys on, fallback counters, the ship-latency histogram,
    # and per-pool occupancy gauges the pool-sizing autoscaler reads.
    disagg = snap.get("disagg") or {}
    if disagg:
        for name, key, help_text in (
            ("kafka_tpu_disagg_shipped_runs_total", "disagg_shipped_runs",
             "Page runs shipped from prefill-pool to decode-pool "
             "replicas."),
            ("kafka_tpu_disagg_shipped_pages_total",
             "disagg_shipped_pages", "KV pages shipped across replicas."),
            ("kafka_tpu_disagg_shipped_bytes_total",
             "disagg_shipped_bytes",
             "Bytes shipped across replicas (real, unpadded)."),
            ("kafka_tpu_disagg_ship_failures_total",
             "disagg_ship_failures",
             "Torn/failed cross-replica ships (thread degraded to "
             "re-prefill; never partial KV)."),
        ):
            if key in disagg:
                w.family(name, "counter", help_text)
                w.sample(name, disagg[key])
        w.family("kafka_tpu_disagg_fallback_total", "counter",
                 "Hand-off fallbacks by kind: prefill_in_place = short "
                 "prompts served colocated on the decode pool; "
                 "ship_skip = hand-offs completed without a copy "
                 "(destination warm / no pages / sole survivor).")
        for key, kind in (("disagg_prefill_in_place", "prefill_in_place"),
                          ("disagg_ship_skips", "ship_skip")):
            if key in disagg:
                w.sample("kafka_tpu_disagg_fallback_total", disagg[key],
                         {"kind": kind})
        if "disagg_handoffs" in disagg:
            w.family("kafka_tpu_disagg_handoffs_total", "counter",
                     "Prefill-and-hand-off completions (shipped or "
                     "degraded).")
            w.sample("kafka_tpu_disagg_handoffs_total",
                     disagg["disagg_handoffs"])
        # Ship-transport dimension (ISSUE 19): which transport moved each
        # run — host + device sum to shipped_runs — plus the host-staging
        # high-water gauge (0 under the device transport).
        w.family("kafka_tpu_disagg_ship_runs_by_transport_total",
                 "counter",
                 "Shipped runs by transport: host = staged through a "
                 "numpy copy; device = device-to-device (zero host "
                 "materialization).")
        for key, transport in (("disagg_ship_host_runs", "host"),
                               ("disagg_ship_device_runs", "device")):
            if key in disagg:
                w.sample("kafka_tpu_disagg_ship_runs_by_transport_total",
                         disagg[key], {"transport": transport})
        if "disagg_ship_staging_bytes" in disagg:
            w.family("kafka_tpu_disagg_ship_staging_bytes", "gauge",
                     "Peak host bytes pinned by host-staged ship chunks "
                     "since the last scrape (peak-since-last, re-armed "
                     "on read).")
            w.sample("kafka_tpu_disagg_ship_staging_bytes",
                     disagg["disagg_ship_staging_bytes"])
        if "ship_ms" in disagg:
            w.histogram_family(
                "kafka_tpu_disagg_ship_milliseconds",
                "Cross-replica page-run ship latency (host-staged "
                "gather+scatter, per run).",
                [({}, disagg["ship_ms"])],
            )
        pools = disagg.get("pools") or []
        if pools:
            # one pass per family so each sample name stays a single
            # contiguous group (exposition rule, enforced by the parser)
            w.family("kafka_tpu_disagg_pool_replicas", "gauge",
                     "Replicas per role pool.")
            for pool in pools:
                w.sample("kafka_tpu_disagg_pool_replicas",
                         len(pool.get("replicas") or []),
                         {"role": pool.get("role", "")})
            w.family("kafka_tpu_disagg_pool_queue_depth", "gauge",
                     "Waiting-queue depth per role pool.")
            for pool in pools:
                w.sample("kafka_tpu_disagg_pool_queue_depth",
                         pool.get("queue_depth", 0),
                         {"role": pool.get("role", "")})
            w.family("kafka_tpu_disagg_pool_occupancy", "gauge",
                     "Mean busy decode slots per step, per role pool.")
            for pool in pools:
                w.sample("kafka_tpu_disagg_pool_occupancy",
                         pool.get("batch_occupancy", 0),
                         {"role": pool.get("role", "")})

    # Flight-recorder anomaly detectors (runtime/metrics.ANOMALY_METRIC_
    # KEYS — the registry a static test enforces in both files).  The
    # counters are edge-triggered firings; the gauge is how many
    # detectors are CURRENTLY firing (the autoscaler's "don't scale on
    # stale math" input, also in /admin/signals).
    anom = snap.get("anomalies") or {}
    if anom:
        w.family("kafka_tpu_anomalies_total", "counter",
                 "Scheduler anomaly detector firings by kind "
                 "(edge-triggered).")
        for key, kind in (
            ("anomaly_queue_stall", "queue_stall"),
            ("anomaly_fetch_starvation", "fetch_starvation"),
            ("anomaly_mfu_collapse", "mfu_collapse"),
            ("anomaly_prefill_convoy", "prefill_convoy"),
            ("anomaly_compile_storm", "compile_storm"),
            ("anomaly_hbm_pressure", "hbm_pressure"),
        ):
            if key in anom:
                w.sample("kafka_tpu_anomalies_total", anom[key],
                         {"kind": kind})
        if "anomalies_active" in anom:
            w.family("kafka_tpu_anomalies_active", "gauge",
                     "Anomaly detectors currently firing.")
            w.sample("kafka_tpu_anomalies_active",
                     anom["anomalies_active"])

    # Flight recorder ring state (runtime/metrics.FLIGHT_METRIC_KEYS);
    # the record contents live at GET /debug/flight/{replica}
    fl = snap.get("flight") or {}
    if fl:
        w.family("kafka_tpu_flight_ring_size", "gauge",
                 "Configured flight-recorder ring length (records; "
                 "summed across DP replicas).")
        w.sample("kafka_tpu_flight_ring_size",
                 fl.get("flight_ring_size", 0))
        w.family("kafka_tpu_flight_records_total", "counter",
                 "Scheduler iterations recorded by the flight recorder.")
        w.sample("kafka_tpu_flight_records_total",
                 fl.get("flight_records", 0))
        w.family("kafka_tpu_flight_postmortems_total", "counter",
                 "Flight-recorder postmortem dumps written.")
        w.sample("kafka_tpu_flight_postmortems_total",
                 fl.get("flight_postmortems", 0))

    # Autoscaler control loop (runtime/metrics.AUTOSCALER_METRIC_KEYS —
    # the registry tests/test_autoscaler.py enforces in both files;
    # present only when KAFKA_TPU_AUTOSCALE runs a controller).  Event
    # counters under one family; the ladder rung and last-observed dp
    # are gauges a dashboard alerts on directly.
    scaler = snap.get("autoscaler") or {}
    if scaler:
        w.family("kafka_tpu_autoscaler_events_total", "counter",
                 "Autoscaler control-loop events by kind.")
        for key, event in (
            ("autoscaler_polls", "poll"),
            ("autoscaler_scale_outs", "scale_out"),
            ("autoscaler_scale_ins", "scale_in"),
            ("autoscaler_resize_failures", "resize_failure"),
            ("autoscaler_degrades", "degrade"),
            ("autoscaler_recovers", "recover"),
            ("autoscaler_vetoes", "veto"),
            ("autoscaler_drains", "drain"),
        ):
            if key in scaler:
                w.sample("kafka_tpu_autoscaler_events_total",
                         scaler[key], {"event": event})
        if "autoscaler_ladder_level" in scaler:
            w.family("kafka_tpu_autoscaler_ladder_level", "gauge",
                     "Current degradation-ladder rung (0 = normal).")
            w.sample("kafka_tpu_autoscaler_ladder_level",
                     scaler["autoscaler_ladder_level"])
        if "autoscaler_dp" in scaler:
            w.family("kafka_tpu_autoscaler_dp", "gauge",
                     "dp at the controller's last signal poll.")
            w.sample("kafka_tpu_autoscaler_dp", scaler["autoscaler_dp"])

    # Compile observatory (runtime/metrics.COMPILE_METRIC_KEYS — the
    # registry tests/test_device_truth.py enforces in both files;
    # process-wide, merged into the snapshot by server/app.py).  The
    # total counter carries the {cache, phase} label matrices; the
    # storm gauge is the autoscaler's "don't resize mid-storm" input.
    comp = snap.get("compiles") or {}
    if comp:
        w.family("kafka_tpu_compiles_total", "counter",
                 "XLA compilations observed, by persistent-cache "
                 "disposition and engine phase.")
        for cache, n in (comp.get("by_cache") or {}).items():
            w.sample("kafka_tpu_compiles_total", n, {"cache": cache})
        for phase, n in (comp.get("by_phase") or {}).items():
            w.sample("kafka_tpu_compiles_total", n, {"phase": phase})
        if "compile_seconds_total" in comp:
            w.family("kafka_tpu_compile_seconds_total", "counter",
                     "Wall-clock seconds spent in XLA compilation.")
            w.sample("kafka_tpu_compile_seconds_total",
                     comp["compile_seconds_total"])
        if "compile_storm_active" in comp:
            w.family("kafka_tpu_compile_storm_active", "gauge",
                     "Compile storm condition currently held "
                     "(recompiles under live traffic).")
            w.sample("kafka_tpu_compile_storm_active",
                     comp["compile_storm_active"])
        if "compile_storms_total" in comp:
            w.family("kafka_tpu_compile_storms_total", "counter",
                     "Compile storm episodes entered.")
            w.sample("kafka_tpu_compile_storms_total",
                     comp["compile_storms_total"])

    # Live HBM accounting (runtime/metrics.MEMORY_METRIC_KEYS, fed by
    # runtime/planner.MemoryMonitor at step cadence).  Gauges are the
    # worst device's numbers; the component family reconciles measured
    # bytes against the MemoryPlan's line items.
    mem = snap.get("memory") or {}
    if mem:
        for key, help_text in (
            ("hbm_bytes_in_use", "Live HBM bytes in use (worst "
             "device; source=plan on chips without memory_stats)."),
            ("hbm_bytes_peak", "Peak HBM bytes in use (worst device)."),
            ("hbm_bytes_limit", "HBM byte limit (smallest device)."),
            ("hbm_headroom_bytes", "Measured HBM headroom: limit - "
             "in_use (size against this, not the plan)."),
            ("hbm_plan_skew", "Measured bytes / MemoryPlan predicted "
             "bytes (1.0 = the plan was right)."),
            ("hbm_pressure", "Headroom under the watermark "
             "(KAFKA_TPU_HBM_WATERMARK)."),
        ):
            if key in mem:
                w.family(f"kafka_tpu_{key}", "gauge", help_text)
                w.sample(f"kafka_tpu_{key}", mem[key])
        components = mem.get("hbm_component_bytes") or {}
        if components:
            w.family("kafka_tpu_hbm_component_bytes", "gauge",
                     "HBM attribution by MemoryPlan line item "
                     "(unattributed = measured residual: gather "
                     "staging, scratch, fragmentation).")
            for comp_name, b in components.items():
                w.sample("kafka_tpu_hbm_component_bytes", b,
                         {"component": comp_name})

    # Agent-native scheduling (runtime/metrics.AGENT_METRIC_KEYS — the
    # registry tests/test_agent_sched.py enforces in both files; all
    # zeros unless KAFKA_TPU_AGENT_DEMOTE is set or background-class
    # requests ran).  Event counters under one family; the awaiting /
    # queue-depth gauges stand alone so the autoscaler contract
    # ("awaiting-tool threads are not load") reads directly.
    ag = snap.get("agent") or {}
    if ag:
        w.family("kafka_tpu_agent_events_total", "counter",
                 "Agent tool-gap scheduling events by kind.")
        for key, event in (
            ("agent_gaps", "gap"),
            ("agent_gap_demotions", "demote"),
            ("agent_gap_cancelled", "cancel"),
            ("agent_hint_hits", "hint_hit"),
            ("agent_hint_misses", "hint_miss"),
        ):
            if key in ag:
                w.sample("kafka_tpu_agent_events_total", ag[key],
                         {"event": event})
        if "agent_gap_pages_demoted" in ag:
            w.family("kafka_tpu_agent_gap_pages_demoted_total", "counter",
                     "KV pages freed from HBM by tool-gap demotions.")
            w.sample("kafka_tpu_agent_gap_pages_demoted_total",
                     ag["agent_gap_pages_demoted"])
        if "agent_gap_bytes_demoted" in ag:
            w.family("kafka_tpu_agent_gap_bytes_demoted_total", "counter",
                     "KV bytes moved down-tier by tool-gap demotions.")
            w.sample("kafka_tpu_agent_gap_bytes_demoted_total",
                     ag["agent_gap_bytes_demoted"])
        if "agent_awaiting_threads" in ag:
            w.family("kafka_tpu_agent_awaiting_threads", "gauge",
                     "Threads mid-tool-gap (lingering or demoted); not "
                     "load — the autoscaler must not count them.")
            w.sample("kafka_tpu_agent_awaiting_threads",
                     ag["agent_awaiting_threads"])
        if "agent_awaiting_bytes" in ag:
            w.family("kafka_tpu_agent_awaiting_bytes", "gauge",
                     "Demoted KV bytes parked in lower tiers awaiting "
                     "a tool return.")
            w.sample("kafka_tpu_agent_awaiting_bytes",
                     ag["agent_awaiting_bytes"])
        if "bg_queue_depth" in ag:
            w.family("kafka_tpu_bg_queue_depth", "gauge",
                     "Background-class requests queued (admit only "
                     "into idle capacity).")
            w.sample("kafka_tpu_bg_queue_depth", ag["bg_queue_depth"])
        w.family("kafka_tpu_bg_events_total", "counter",
                 "Background-class scheduling events by kind.")
        for key, event in (
            ("bg_admitted", "admit"),
            ("bg_chunks", "chunk"),
            ("bg_yields", "yield"),
        ):
            if key in ag:
                w.sample("kafka_tpu_bg_events_total", ag[key],
                         {"event": event})

    sandbox = snap.get("sandbox") or {}
    if sandbox:
        w.family("kafka_tpu_sandbox_total", "counter",
                 "Sandbox subprocess supervision events.")
        for kind, v in sandbox.items():
            w.sample("kafka_tpu_sandbox_total", v, {"event": kind})

    sup = snap.get("replica_supervisor") or {}
    if sup:
        w.family("kafka_tpu_replica_health", "gauge",
                 "Per-replica health (1 healthy, 0.5 probation, 0 out).")
        for i, g in enumerate(sup.get("health", [])):
            w.sample("kafka_tpu_replica_health", g, {"replica": i})
        w.family("kafka_tpu_replica_supervisor_total", "counter",
                 "Replica supervision events.")
        for kind in ("quarantines", "readmits", "waiting_migrated",
                     "affinity_resteered", "rebuilds",
                     "replica_rebuilds"):
            if kind in sup:
                w.sample("kafka_tpu_replica_supervisor_total", sup[kind],
                         {"event": kind})

    tr = snap.get("tracing") or {}
    if tr:
        w.family("kafka_tpu_traces_total", "counter",
                 "Traces started since boot.")
        w.sample("kafka_tpu_traces_total", tr.get("traces", 0))
        w.family("kafka_tpu_stitched_spans_total", "counter",
                 "Cross-process spans stitched into parent traces.")
        w.sample("kafka_tpu_stitched_spans_total",
                 tr.get("stitched_spans", 0))

    return w.render()
