"""Server-sent-events framing for aiohttp.

Wire protocol parity with the reference (SURVEY §5.8): `data:`-framed JSON
events terminated by `data: [DONE]`; event kinds are OpenAI chunks,
`tool_result`, `tool_messages`, `agent_done`, and `error`.  Errors inside a
generator are serialized as an `error` event followed by [DONE] so clients
always terminate cleanly (reference server.py:199-201, :375-377).
"""

from __future__ import annotations

import json
import logging
from typing import Any, AsyncIterator, Dict

from aiohttp import web

logger = logging.getLogger("kafka_tpu.server.sse")

DONE_FRAME = b"data: [DONE]\n\n"


def frame(payload: Any) -> bytes:
    if isinstance(payload, str):
        return f"data: {payload}\n\n".encode()
    return b"data: " + json.dumps(payload, separators=(",", ":")).encode() + b"\n\n"


async def sse_response(
    request: web.Request,
    events: AsyncIterator[Dict[str, Any]],
) -> web.StreamResponse:
    """Stream `events` (already-wire-shaped dicts) as SSE, then [DONE]."""
    resp = web.StreamResponse(
        status=200,
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "Connection": "keep-alive",
            "X-Accel-Buffering": "no",
        },
    )
    await resp.prepare(request)
    try:
        async for event in events:
            await resp.write(frame(event))
    except ConnectionResetError:
        logger.info("client disconnected mid-stream")
        return resp
    except Exception as e:
        logger.exception("error during SSE stream")
        try:
            await resp.write(frame({"type": "error", "error": str(e)}))
        except ConnectionResetError:
            return resp
    finally:
        # Close the pipeline NOW, not at GC: on client disconnect this is
        # what propagates cancellation down to the engine (agent generator →
        # provider stream finally → worker.cancel), freeing the batch slot
        # instead of decoding the rest of the request for a dead socket.
        aclose = getattr(events, "aclose", None)
        if aclose is not None:
            await aclose()
    try:
        await resp.write(DONE_FRAME)
        await resp.write_eof()
    except ConnectionResetError:
        pass
    return resp
