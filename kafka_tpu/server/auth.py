"""Password + session-token primitives for the playground login.

The reference's playground authenticated users through Supabase email
sessions (playground/src/components/auth-provider.tsx:19-40) — an external
service.  Here the user store is the DB tier (db/base.py contract) and the
crypto is stdlib: scrypt password hashing with a per-user salt, and
unguessable urlsafe session tokens.  The server keeps its static
`api_token` tier (machine clients); session tokens are the human tier.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
import time

SESSION_TTL_S = 30 * 24 * 3600

# scrypt cost: interactive-login tier (~50 ms); N is the CPU/memory cost
_SCRYPT = dict(n=2**14, r=8, p=1)


def new_salt() -> str:
    return secrets.token_hex(16)


def hash_password(password: str, salt: str) -> str:
    return hashlib.scrypt(
        password.encode(), salt=bytes.fromhex(salt), **_SCRYPT
    ).hex()


def verify_password(password: str, salt: str, expected_hash: str) -> bool:
    got = hash_password(password, salt)
    return hmac.compare_digest(got, expected_hash)


def new_session_token() -> str:
    return f"sess_{secrets.token_urlsafe(32)}"


def session_expiry() -> float:
    return time.time() + SESSION_TTL_S
