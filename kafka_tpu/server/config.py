"""Typed serving configuration.

The reference's config was env vars + code constants (SURVEY §5.6); here
it's one dataclass with env-var overrides, covering the engine shape, model
selection, and server knobs.  Per-thread config stays in the DB tier.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple


@dataclasses.dataclass
class ServingConfig:
    # model
    model_name: str = "llama-3.2-1b"
    checkpoint_dir: Optional[str] = None  # HF safetensors dir; None=random init
    dtype: str = "bfloat16"
    # weight-only quantization: "" (bf16) or "int8" (models/quant.py) —
    # halves decode weight traffic and fits Llama-3-8B on one v5e chip
    quantize: str = ""
    # KV-cache quantization: "" or "int8" (per-slot scales,
    # runtime/kv_cache.py) — halves KV window traffic and doubles how many
    # context windows a pool holds; attention runs the XLA gather path
    kv_quantize: str = ""
    # engine shape
    max_batch: int = 8
    page_size: int = 16
    num_pages: int = 2048
    max_pages_per_seq: int = 512
    prefill_buckets: Tuple[int, ...] = (64, 128, 256, 512, 1024, 2048)
    max_new_tokens_default: int = 1024
    # fused decode depth (EngineConfig.multi_step): steps per device
    # dispatch when the batch is busy; 1 disables fusion
    multi_step: int = 16
    # Draft-free speculative decoding (KAFKA_TPU_SPECULATIVE_K): up to K
    # n-gram prompt-lookup candidates per lane verified in one [B, K+1]
    # device dispatch (README "Speculative decoding").  0 (default)
    # disables it entirely — no verify program is compiled and the
    # dispatch paths are the plain ones.  Best on the repetition-heavy
    # agent workload (tool echoes, JSON, code spans); leave it off for
    # high-entropy creative sampling.
    speculative_k: int = 0
    # Radix prefix-cache page budget (KAFKA_TPU_PREFIX_CACHE_PAGES): how
    # many KV pool pages the cross-thread prefix cache may retain.  None =
    # bounded only by pool pressure (the engine reclaims cache pages
    # before it ever preempts a live request); 0 disables the cache.
    # Replaces the old per-thread entry-count cap — pages are what the
    # pool actually runs out of.
    prefix_cache_pages: Optional[int] = None
    # Tiered KV cache (KAFKA_TPU_KV_HOST_TIER_MB, README "KV tiering"):
    # host-RAM page tier under the pool, in MiB PER ENGINE REPLICA.
    # Prefix-cache eviction demotes page runs host-side; a returning
    # thread's lookup promotes them back instead of re-prefilling.  0
    # (default) disables the tier — all paths byte-identical to before.
    kv_host_tier_mb: int = 0
    # Disk spill dir below the host tier (KAFKA_TPU_KV_DISK_TIER_DIR):
    # host-budget overflow spills runs here (second-chance LRU) and the
    # tracing span ring persists alongside.  None = drop on overflow.
    kv_disk_tier_dir: Optional[str] = None
    # Object-store KV tier (KAFKA_TPU_KV_OBJECT_DIR, README "Object-store
    # KV tier"): a SHARED directory (or bucket mount) below host+disk
    # that makes thread state portable across hosts — runs are archived
    # content-addressed (identical prefixes dedupe across replicas/hosts)
    # and per-thread sleep manifests let dormant threads wake on ANY
    # replica with cache_source="object_tier" instead of re-prefilling.
    # POST /admin/drain/{replica} flushes a replica's warm state before
    # the autoscaler shrinks it away.  None (default) disables the tier;
    # every dispatch/eviction path is byte-identical to before.
    # An http(s):// value mounts the S3-shaped HTTPObjectStore instead
    # of a directory.  Either backend is wrapped in the StoreGuard
    # resilience layer (README "Object store resilience"), tuned by:
    #   KAFKA_TPU_KV_OBJECT_TIMEOUT_S          per-op deadline (0 = off)
    #   KAFKA_TPU_KV_OBJECT_RETRIES            retry budget (default 2)
    #   KAFKA_TPU_KV_OBJECT_BACKOFF_S          base backoff (default .05)
    #   KAFKA_TPU_KV_OBJECT_BREAKER_FAILURES   breaker trip (default 5)
    #   KAFKA_TPU_KV_OBJECT_BREAKER_OPEN_S     open window (default 10)
    #   KAFKA_TPU_KV_OBJECT_SCRUB_S            in-process janitor cadence
    #                                          (0 = off; prefer scheduling
    #                                          scripts/objstore_fsck.py)
    #   KAFKA_TPU_KV_OBJECT_SCRUB_GRACE_S      janitor grace (default 3600)
    kv_object_dir: Optional[str] = None
    # Byte budget (MiB) on the object-store references each replica holds
    # (second-chance LRU; the last dropped reference deletes the object).
    # 0 = unbounded.  KAFKA_TPU_KV_OBJECT_MB.
    kv_object_mb: int = 0
    # parallelism (SURVEY §2.2): the server builds its mesh from these.
    #   tp — tensor parallel within each engine (attention heads / MLP)
    #   sp — sequence parallel: ring-sharded chunked prefill for long
    #        prompts, composed with tp inside the same engine
    #   pp — pipeline parallel: layer stages sharded across devices for
    #        models exceeding one slice's HBM (parallel/pipeline.py);
    #        composes with tp, not with sp or dp
    #   dp — data parallel: dp independent engine replicas, each over its
    #        own tp*sp device slice, with thread-affinity request routing
    #        (runtime/dp_router.py).  dp*pp*sp*tp devices total.
    #   ep — expert parallel (MoE): expert weights shard over "ep" for
    #        Mixtral-class models; composes with tp (and dp replicas)
    tp_size: int = 1
    sp_size: int = 1
    pp_size: int = 1
    dp_size: int = 1
    ep_size: int = 1
    # Disaggregated prefill/decode (KAFKA_TPU_DP_ROLES, README
    # "Disaggregated prefill/decode"): "prefill:P,decode:D" splits the dp
    # fleet into role-specialized pools — long prefills run on the
    # prefill pool and their KV pages ship to a decode-pool replica at
    # first-token time, protecting decode-lane TPOT from prefill
    # interference (DistServe/Mooncake).  P+D must equal dp_size.  None
    # (default) = colocated serving, byte-identical to before.
    dp_roles: Optional[str] = None
    # Prompts whose UNCACHED prefill span is below this many tokens
    # prefill in place on the decode pool (shipping must never cost more
    # than it saves).  KAFKA_TPU_DISAGG_MIN_PREFILL_TOKENS.
    disagg_min_prefill_tokens: int = 512
    # long-context CP strategy when sp>1: "ring" or "ulysses"
    cp_strategy: str = "ring"
    # Request-lifecycle hardening (runtime/failpoints.py chaos-tests these
    # paths; README "Failure semantics"):
    #   max_ttft_s — a request still awaiting its FIRST token past this
    #       many seconds finishes with finish_reason="timeout" (None = off)
    #   request_timeout_s — total wall-time bound per request (None = off)
    #   max_queue_depth — bounded engine waiting queue; a submit past it
    #       answers HTTP 429 + Retry-After (0 = unbounded)
    #   drain_timeout_s — graceful-shutdown budget: /health flips to
    #       "draining", admission stops, in-flight streams get this long
    #       to finish before they are cancelled
    max_ttft_s: Optional[float] = None
    request_timeout_s: Optional[float] = None
    max_queue_depth: int = 256
    drain_timeout_s: float = 30.0
    # Cross-process fault tolerance (README "Process boundaries"):
    #   replica_quarantine_threshold — consecutive step failures before a
    #       DP replica is circuit-broken out of the router (probation +
    #       warm re-admit after a doubling backoff window).  Sandbox
    #       subprocess supervision is configured where the factory lives,
    #       straight from KAFKA_TPU_SANDBOX_RESTART_BACKOFF_S /
    #       KAFKA_TPU_SANDBOX_MAX_RESTARTS (sandbox/process.py) — no
    #       config field here, the server never constructs that factory.
    replica_quarantine_threshold: int = 3
    #   replica_rebuild_threshold — quarantine escalation: after this many
    #       quarantine TRIPS the supervisor rebuilds the replica's engine
    #       at window expiry (DataParallelEngines._rebuild_replica)
    #       instead of re-admitting it forever (0 disables).
    replica_rebuild_threshold: int = 3
    # Autoscaler control loop (KAFKA_TPU_AUTOSCALE, README "Autoscaler",
    # ISSUE 13): "off" (default — no controller built, every dispatch and
    # admission path byte-identical to before), "recommend" (full
    # decision loop + GET /admin/autoscaler log, no action taken — the
    # dry-run to watch before handing over the keys), or "act" (closes
    # the loop: scale-out/in through /admin/resize's seam, degradation
    # ladder under overload).  Poll cadence, hysteresis bands, cooldowns
    # and dp bounds come from KAFKA_TPU_AUTOSCALE_* (runtime/
    # autoscaler.AutoscalerConfig.from_env).
    autoscale: str = "off"
    # Observability (README "Observability"):
    #   trace_sample — fraction of requests traced end to end (span tree in
    #       the /debug/trace ring).  1.0 traces everything (the sampling-
    #       down knob is what's disabled by default); 0 disables tracing.
    #   trace_ring — how many finished traces the in-memory ring retains.
    #   slow_ttft_ms / slow_total_ms — requests exceeding either threshold
    #       emit ONE structured log line with their full span breakdown and
    #       count in requests.slow (None = off).
    #   log_format — "json" stamps every log record with trace_id/span_id/
    #       thread_id (kafka_tpu/logs.py); "text" keeps stdlib formatting.
    trace_sample: float = 1.0
    trace_ring: int = 256
    slow_ttft_ms: Optional[float] = None
    slow_total_ms: Optional[float] = None
    log_format: str = "text"
    # Scheduler flight recorder (README "Flight recorder", ISSUE 11):
    # per-replica ring of this many per-scheduler-iteration records
    # (decision log, measured dispatch timing, anomaly detectors,
    # postmortem capture at GET /debug/flight/{replica}).  0 disables it
    # with byte-identical dispatch paths; None defers to
    # KAFKA_TPU_FLIGHT_RING (default 256).
    flight_ring: Optional[int] = None
    # SLO targets (README "SLO telemetry", ISSUE 10): every request is
    # classified MET/MISSED at finalize against these; /metrics exports
    # attainment (total/1m/5m windows) and goodput (tokens from SLO-met
    # requests), and /admin/signals feeds them to the autoscaler.
    #   slo_ttft_ms — time-to-first-token target (default 200, the
    #       BASELINE north star; 0 disables the TTFT check)
    #   slo_tpot_ms — per-output-token target (default 0 = disabled;
    #       set it to bound decode-cadence SLOs, e.g. 50 for p99 TPOT)
    # None here = defer to KAFKA_TPU_SLO_TTFT_MS / KAFKA_TPU_SLO_TPOT_MS
    # (runtime/metrics.py reads them at engine construction).
    slo_ttft_ms: Optional[float] = None
    slo_tpot_ms: Optional[float] = None
    # server
    host: str = "0.0.0.0"
    port: int = 8000
    # optional bearer-token auth for /v1/* + /metrics (playground parity
    # with the reference's authed deployment; None = open, the dev default)
    api_token: Optional[str] = None
    db_path: str = "data/threads.db"
    local_sandbox_url: Optional[str] = None
    cors_origins: str = "*"
    # test/dev: tiny random model instead of a real checkpoint
    tiny_model: bool = False
    # Static system prompt bypassing the sectioned prompt provider
    # (reference src/kafka/v1.py:85 / src/agents/base.py:102-104 had the
    # same seam).  None = the full PromptProviderV1 persona.  Benchmarks
    # use it to keep the served prompt a realistic size under the
    # byte-level tokenizer.
    system_prompt: Optional[str] = None
    # compile the serving programs at boot (one tiny generation per engine)
    # so the first real request doesn't pay the 20-40s XLA compile
    warmup: bool = True
    # persistent XLA compilation cache: warm reboots reuse compiled
    # programs from disk instead of recompiling every bucket ("" disables).
    # The default honors KAFKA_TPU_COMPILE_CACHE at CONSTRUCTION time (not
    # just via from_env): the test suite points it at a fresh per-run dir
    # because a shared on-disk cache can hold executables AOT-compiled on
    # a different host of a migrating environment, and XLA hard-aborts
    # (uncatchably) loading one with mismatched machine features.
    compile_cache_dir: str = dataclasses.field(
        default_factory=lambda: os.environ.get(
            "KAFKA_TPU_COMPILE_CACHE", "~/.cache/kafka_tpu/xla"
        )
    )

    @classmethod
    def profile_32k(cls, **overrides) -> "ServingConfig":
        """BASELINE config 5's serving shape: 32k-context Llama-3-70B on a
        tp x sp mesh (v5p-64-class slice).

        Window math: page_size 16 x max_pages_per_seq 2048 = 32768-token
        attention window.  The pool holds num_pages = 4 full windows + 1
        trash page so a handful of long threads coexist (KV for 70B at 32k
        is ~20 GB/seq in bf16 across the slice — the pool, like the
        weights, is sharded over tp so each device holds 1/tp of it).
        Prefill buckets run to 4096 and every bucket divides sp=4: the
        ring shards each chunk across the sp axis (engine constructor
        contract), and chunked prefill walks the prompt 4096 tokens at a
        time.  dp/pp stay 1 — long-context serving spends the mesh on
        tp x sp (SURVEY §2.2, ring CP for prefill beyond one chip's HBM).
        """
        cfg = cls(
            model_name="llama-3-70b",
            tp_size=16,
            sp_size=4,
            max_batch=4,
            page_size=16,
            max_pages_per_seq=2048,
            num_pages=4 * 2048 + 1,
            prefill_buckets=(256, 1024, 2048, 4096),
            max_new_tokens_default=2048,
        )
        return dataclasses.replace(cfg, **overrides)

    @classmethod
    def from_env(cls, **overrides) -> "ServingConfig":
        env = os.environ

        def get(name: str, default, cast=str):
            raw = env.get(f"KAFKA_TPU_{name}")
            return cast(raw) if raw is not None else default

        def get_axis(name: str, default: int) -> int:
            # both spellings work: KAFKA_TPU_DP_SIZE=2 and KAFKA_TPU_DP=2
            raw = env.get(f"KAFKA_TPU_{name}_SIZE", env.get(f"KAFKA_TPU_{name}"))
            return int(raw) if raw is not None else default

        cfg = cls(
            model_name=get("MODEL", cls.model_name),
            checkpoint_dir=get("CHECKPOINT_DIR", None),
            max_batch=get("MAX_BATCH", cls.max_batch, int),
            num_pages=get("NUM_PAGES", cls.num_pages, int),
            max_pages_per_seq=get("MAX_PAGES_PER_SEQ", cls.max_pages_per_seq, int),
            multi_step=get("MULTI_STEP", cls.multi_step, int),
            # clamp negatives to 0 = disabled (same policy as the cache
            # budget below: nonsense env values must not half-enable)
            speculative_k=get("SPECULATIVE_K", cls.speculative_k,
                              lambda v: max(0, int(v))),
            # clamp nonsense (negative) values to 0 = "disabled" — a raw
            # negative budget would otherwise evict every store on sight
            # while leaving the cache machinery running
            prefix_cache_pages=get("PREFIX_CACHE_PAGES", None,
                                   lambda v: max(0, int(v))),
            # clamp negatives to 0 = disabled, same policy as above
            kv_host_tier_mb=get("KV_HOST_TIER_MB", cls.kv_host_tier_mb,
                                lambda v: max(0, int(v))),
            kv_disk_tier_dir=get("KV_DISK_TIER_DIR", None),
            kv_object_dir=get("KV_OBJECT_DIR", None),
            # clamp negatives to 0 = unbounded refs, same env policy
            kv_object_mb=get("KV_OBJECT_MB", cls.kv_object_mb,
                             lambda v: max(0, int(v))),
            tp_size=get_axis("TP", cls.tp_size),
            sp_size=get_axis("SP", cls.sp_size),
            pp_size=get_axis("PP", cls.pp_size),
            dp_size=get_axis("DP", cls.dp_size),
            ep_size=get_axis("EP", cls.ep_size),
            dp_roles=get("DP_ROLES", None),
            disagg_min_prefill_tokens=get(
                "DISAGG_MIN_PREFILL_TOKENS",
                cls.disagg_min_prefill_tokens,
                lambda v: max(1, int(v))),
            cp_strategy=get("CP_STRATEGY", cls.cp_strategy),
            max_ttft_s=get("MAX_TTFT_S", None, float),
            request_timeout_s=get("REQUEST_TIMEOUT_S", None, float),
            max_queue_depth=get("MAX_QUEUE_DEPTH", cls.max_queue_depth, int),
            drain_timeout_s=get("DRAIN_TIMEOUT_S", cls.drain_timeout_s,
                                float),
            replica_quarantine_threshold=get(
                "REPLICA_QUARANTINE_THRESHOLD",
                cls.replica_quarantine_threshold, int),
            # clamp negatives to 0 = disabled, same policy as the caches
            replica_rebuild_threshold=get(
                "REPLICA_REBUILD_THRESHOLD",
                cls.replica_rebuild_threshold,
                lambda v: max(0, int(v))),
            autoscale=get("AUTOSCALE", cls.autoscale),
            trace_sample=get("TRACE_SAMPLE", cls.trace_sample, float),
            trace_ring=get("TRACE_RING", cls.trace_ring, int),
            slow_ttft_ms=get("SLOW_TTFT_MS", None, float),
            slow_total_ms=get("SLOW_TOTAL_MS", None, float),
            # clamp negatives to 0 = disabled, same policy as the caches
            flight_ring=get("FLIGHT_RING", None,
                            lambda v: max(0, int(v))),
            slo_ttft_ms=get("SLO_TTFT_MS", None, float),
            slo_tpot_ms=get("SLO_TPOT_MS", None, float),
            log_format=get("LOG_FORMAT", cls.log_format),
            host=get("HOST", cls.host),
            port=get("PORT", cls.port, int),
            api_token=get("API_TOKEN", None),
            db_path=get("DB_PATH", cls.db_path),
            local_sandbox_url=get("SANDBOX_URL", None),
            tiny_model=get("TINY_MODEL", "0") in ("1", "true", "True"),
            system_prompt=get("SYSTEM_PROMPT", None),
            quantize=get("QUANTIZE", cls.quantize),
            kv_quantize=get("KV_QUANTIZE", cls.kv_quantize),
            warmup=get("WARMUP", "1") not in ("0", "false", "False"),
            # compile_cache_dir omitted: its default_factory already reads
            # KAFKA_TPU_COMPILE_CACHE
        )
        return dataclasses.replace(cfg, **overrides)
