"""The HTTP API server: OpenAI-compatible endpoints + the 4-event SSE
protocol, served by aiohttp.

Endpoint parity with the reference (server.py:384-620):
  POST /v1/chat/completions                  stateless chat (agent loop)
  POST /v1/threads/{id}/chat/completions     thread chat w/ history
  POST /v1/agent/run                         stateless agent run (SSE)
  POST /v1/threads/{id}/agent/run            thread agent run (SSE)
  POST /v1/threads                           create thread
  GET  /v1/threads                           list threads
  GET  /v1/threads/{id}                      thread metadata
  GET  /v1/threads/{id}/messages             thread history
  DELETE /v1/threads/{id}                    delete thread
  DELETE /v1/threads/{id}/messages           clear history
  PUT  /v1/threads/{id}/config               set per-thread config (ext.)
  GET  /v1/models                            served models
  GET  /health                               liveness + engine stats

One deliberate improvement over the reference: the chat path streams REAL
tokens as they decode.  The reference ran the whole agent loop first and
then re-streamed the final text in 20-char pseudo-chunks
(server.py:347-356) — its TTFT was a full agent run.  Clients still get the
same event vocabulary (OpenAI chunks / tool_result / tool_messages /
agent_done, SURVEY §5.8), so the reference playground works unmodified.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import logging
from dataclasses import replace as dataclasses_replace
from typing import Any, AsyncIterator, Dict, List, Optional

from aiohttp import web
from pydantic import ValidationError

from ..core.types import (
    ContextLengthError,
    LLMProviderError,
    ServerOverloadedError,
    Usage,
    new_completion_id,
)
from .. import tracing
from ..core.wire import AgentRunRequest, ChatCompletionRequest
from ..db import DBClient, LocalDBClient, make_db_client
from ..kafka import KafkaV1Provider, MessageAccumulator
from ..llm.base import LLMProvider
from ..tools import MCPServerConfig, Tool
from .config import ServingConfig
from .sse import sse_response

logger = logging.getLogger("kafka_tpu.server")

STATE_KEY = web.AppKey("kafka_tpu_state", dict)


# ---------------------------------------------------------------------------
# wiring
# ---------------------------------------------------------------------------


def build_tpu_provider(cfg: ServingConfig) -> LLMProvider:
    """Construct tokenizer + engine + provider per the serving config.

    Parallelism wiring (the reference wired its whole stack in the server
    lifespan, server.py:89-150 — here the mesh shape is the analog):
    tp/sp build one SPMD engine over a tp×sp mesh; dp>1 builds dp replica
    engines over disjoint tp×sp device slices behind the thread-affinity
    router (runtime/dp_router.py).  Multi-host topologies initialize
    jax.distributed first (env-driven, no-op single-process).

    Multi-host + dp: replicas are per-process objects (each owns a Python
    scheduler thread), so each server process builds its replicas over its
    own *local* chips and an external load balancer spreads traffic across
    the hosts — dp_size here is replicas per host.  tp/sp SPMD engines, by
    contrast, span the global device set the way jax.distributed programs
    do.
    """
    import jax

    from ..llm.tpu_provider import TPULLMProvider
    from ..models import get_config, init_params, load_checkpoint
    from ..models.tokenizer import ByteTokenizer, load_tokenizer
    from ..parallel.distributed import init_distributed
    from ..runtime import EngineConfig, InferenceEngine

    # before any backend use: multi-host init when KAFKA_TPU_COORDINATOR /
    # NUM_PROCESSES are set (SURVEY §2.2 "distributed communication
    # backend"); returns False and costs nothing single-process
    init_distributed()

    # compile observatory (runtime/compile_log.py): the ring must exist
    # before the first jax.jit below so boot-phase compiles are captured;
    # KAFKA_TPU_COMPILE_RING=0 leaves it off and every instrument() seam
    # returns the jitted fn unchanged
    from ..runtime import compile_log

    compile_log.init()
    compile_log.set_phase("boot")

    if cfg.compile_cache_dir:
        # persistent XLA compile cache: a warm reboot loads every serving
        # program from disk instead of recompiling (~30s per bucket)
        import os as _os

        cache_dir = _os.path.expanduser(cfg.compile_cache_dir)
        _os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        compile_log.configure_cache(cache_dir)
    else:
        compile_log.configure_cache(None)

    # Resolve the model's ARCHITECTURE cheaply (config.json / registry —
    # no weight materialization) so the memory-fit check below can reject
    # an impossible config in milliseconds, before a multi-GiB checkpoint
    # load ever touches the device.
    if cfg.checkpoint_dir:
        import os as _os

        from ..models.config import config_from_hf_json

        tokenizer = load_tokenizer(cfg.checkpoint_dir)
        model_cfg = config_from_hf_json(
            _os.path.join(cfg.checkpoint_dir, "config.json")
        )
    elif cfg.tiny_model:
        tokenizer = ByteTokenizer()
        model_cfg = get_config("tiny").replace(
            vocab_size=tokenizer.vocab_size, dtype="float32"
        )
    else:
        tokenizer = ByteTokenizer()
        base = get_config(cfg.model_name)
        vocab = max(tokenizer.vocab_size, 262)
        if base.image_token_id is not None:
            # the reserved image-placeholder id must stay in-vocab
            vocab = max(vocab, base.image_token_id + 1)
        model_cfg = base.replace(vocab_size=vocab, dtype=cfg.dtype)
    if cfg.quantize and cfg.quantize != "int8":
        raise ValueError(f"unknown quantize mode {cfg.quantize!r}")

    engine_cfg = EngineConfig(
        max_batch=cfg.max_batch,
        page_size=cfg.page_size,
        num_pages=cfg.num_pages,
        max_pages_per_seq=cfg.max_pages_per_seq,
        prefill_buckets=cfg.prefill_buckets,
        max_new_tokens_default=cfg.max_new_tokens_default,
        cp_strategy=cfg.cp_strategy,
        multi_step=cfg.multi_step,
        speculative_k=cfg.speculative_k,
        kv_quantize=cfg.kv_quantize,
        # 0 disables the radix prefix cache; None = pressure-bounded
        prefix_cache_entries=0 if cfg.prefix_cache_pages == 0 else 64,
        prefix_cache_pages=cfg.prefix_cache_pages or None,
        kv_host_tier_mb=cfg.kv_host_tier_mb,
        kv_disk_tier_dir=cfg.kv_disk_tier_dir,
        kv_object_dir=cfg.kv_object_dir,
        kv_object_mb=cfg.kv_object_mb,
        max_ttft_s=cfg.max_ttft_s,
        max_total_s=cfg.request_timeout_s,
        max_waiting=cfg.max_queue_depth,
    )
    if cfg.flight_ring is not None:
        # None defers to the EngineConfig default (KAFKA_TPU_FLIGHT_RING)
        engine_cfg = dataclasses_replace(engine_cfg,
                                         flight_ring=cfg.flight_ring)
    # Memory-fit validation (runtime/planner.py): per-device bytes under
    # the actual sharding rules, against the live device's HBM.  When the
    # WEIGHTS ALONE exceed the budget — never a false positive, the
    # activation terms are estimates but the weight bytes are exact — fail
    # here, before any weights load.
    memory_plan = None
    try:
        from ..runtime.planner import hbm_for_device, plan_for_serving

        hbm = hbm_for_device(jax.devices()[0])
        if hbm:
            memory_plan = plan_for_serving(
                cfg, hbm_bytes=hbm, model_cfg=model_cfg
            )
            if memory_plan.weight_bytes > memory_plan.usable_bytes:
                raise MemoryError(
                    f"{model_cfg.name} weights alone need "
                    f"{memory_plan.weight_bytes / 2**30:.1f} GiB/device, "
                    f"budget {memory_plan.usable_bytes / 2**30:.1f} GiB: "
                    f"{memory_plan.summary()} — shard (tp/pp), quantize, "
                    "or pick a bigger topology"
                )
            log = logger.warning if not memory_plan.fits else logger.info
            log("memory plan: %s", memory_plan.summary())
    except MemoryError:
        raise
    except Exception as e:
        logger.debug("memory planning skipped: %s", e)

    # NOW materialize weights (checkpoint load / random init); the
    # plan-validated model_cfg is the one served
    if cfg.checkpoint_dir:
        _, params = load_checkpoint(cfg.checkpoint_dir, model_cfg)
    else:
        params = init_params(model_cfg, jax.random.PRNGKey(0))
    if cfg.quantize == "int8":
        from ..models import quantize_params

        params = quantize_params(params, model_cfg)

    if cfg.dp_roles and cfg.dp_size <= 1:
        raise ValueError(
            "KAFKA_TPU_DP_ROLES needs dp_size > 1: role pools split the "
            "DP fleet into prefill and decode replicas"
        )
    if cfg.dp_size > 1:
        if cfg.pp_size > 1:
            raise ValueError(
                "dp_size and pp_size cannot compose: DP replicates whole "
                "engines while PP exists to fit a model that does NOT fit "
                "a replica — pick one"
            )
        from ..runtime.dp_router import DataParallelEngines

        # replica engines cannot place params onto another host's
        # (non-addressable) devices — under multi-host init each process
        # builds dp replicas over its own chips (see docstring)
        local = (
            jax.local_devices() if jax.process_count() > 1 else None
        )
        engine = DataParallelEngines(
            model_cfg, params, engine_cfg,
            dp=cfg.dp_size, tp=cfg.tp_size, sp=cfg.sp_size,
            ep=cfg.ep_size,
            devices=local,
            quarantine_threshold=cfg.replica_quarantine_threshold,
            rebuild_threshold=cfg.replica_rebuild_threshold,
            # disaggregated prefill/decode pools (README "Disaggregated
            # prefill/decode"); None = colocated, byte-identical
            dp_roles=cfg.dp_roles,
            disagg_min_prefill_tokens=cfg.disagg_min_prefill_tokens,
        )
    else:
        mesh = None
        if (cfg.tp_size > 1 or cfg.sp_size > 1 or cfg.pp_size > 1
                or cfg.ep_size > 1):
            from ..parallel import MeshConfig, make_mesh, resolve_tensor_axes

            # grouped GQA: a tensor degree beyond num_kv_heads factorizes
            # into tp*tq so the KV pool shards over tp instead of fully
            # replicating; ulysses/pp keep the plain axis (see
            # parallel/mesh.py resolve_tensor_axes — shared with the
            # memory planner so the plan matches placement)
            tpk, tq = resolve_tensor_axes(
                cfg.tp_size, model_cfg.num_kv_heads,
                cp_strategy=cfg.cp_strategy, sp=cfg.sp_size,
                pp=cfg.pp_size,
            )
            mesh = make_mesh(MeshConfig(
                pp=cfg.pp_size, sp=cfg.sp_size, tp=tpk, tq=tq,
                ep=cfg.ep_size,
            ))
        engine = InferenceEngine(model_cfg, params, engine_cfg, mesh=mesh)
    if memory_plan is not None:
        # live HBM accounting (runtime/planner.py MemoryMonitor): the plan
        # attaches after construction so measured bytes_in_use can report
        # plan_skew against the numbers this deployment was validated on
        for _e in getattr(engine, "engines", [engine]):
            if getattr(_e, "memory_monitor", None) is not None:
                _e.memory_monitor.plan = memory_plan
    if cfg.warmup:
        # Compile the serving programs NOW (engine is not yet driven by the
        # worker thread, so direct generate() is safe); the first real
        # request then pays serving latency, not the XLA compile.  Metrics
        # reset afterwards so /metrics percentiles reflect serving only.
        import time as _time

        from ..runtime import GenRequest
        from ..runtime.metrics import EngineMetrics

        t0 = _time.monotonic()
        compile_log.set_phase("warmup")
        engines = getattr(engine, "engines", [engine])
        # warmup is operator traffic, not client traffic: it must not trip
        # the admission bound (a small max_queue_depth would otherwise
        # reject the multi-stream warmup batch).  All engines share this
        # EngineConfig instance, so flip it once and restore after.
        _admission_bound = engine_cfg.max_waiting
        engine_cfg.max_waiting = 0
        # Every prefill bucket compiles now — a real conversation grows
        # through the bucket ladder, and each uncompiled bucket would cost
        # its first request a ~30s stall.  One prompt per bucket (sized to
        # land in it), plus enough concurrent requests per replica to also
        # compile the fused multi-step decode program (engages at >=3
        # active lanes).  Submitted straight to each replica, with no
        # prefix_key: warmup must not seed the prefix cache or the DP
        # affinity map.
        window = engine_cfg.max_window
        bucket_lens = sorted({
            min(b, window - engine_cfg.multi_step - 4)
            for b in engine_cfg.prefill_buckets
        })
        per_engine = (
            3 if engine_cfg.multi_step > 1 and cfg.max_batch >= 3 else 1
        )
        # grammar artifact for the fsm-program warmup below (None =
        # feature disabled, uncompilable, or no tokenizer eot in vocab)
        _warmup_grammar = None
        from ..llm.constrained import (
            build_tool_call_mask_fn,
            compile_grammar_for_mask_fn,
            grammar_ondevice_enabled,
        )

        if grammar_ondevice_enabled():
            from ..agents.base import IDLE_TOOL

            _warm_tools = [
                t.to_openai() for t in default_builtin_tools(cfg)
            ] + [IDLE_TOOL]
            _warm_mask = build_tool_call_mask_fn(
                tokenizer, _warm_tools, "required"
            )
            if _warm_mask is not None:
                _warmup_grammar = compile_grammar_for_mask_fn(
                    _warm_mask, model_cfg.vocab_size
                )
        for n, e in enumerate(engines):
            for j, blen in enumerate(bucket_lens):
                e.submit(GenRequest(
                    request_id=f"__warmup_b{n}_{j}",
                    prompt_ids=[3] * max(1, blen), max_new_tokens=1,
                ))
                e.run_to_completion()  # one at a time: bounded pool use
            for i in range(per_engine):
                e.submit(GenRequest(
                    request_id=f"__warmup_{n}_{i}",
                    prompt_ids=[3] * min(8, window // 4),
                    max_new_tokens=engine_cfg.multi_step + 2,
                ))
            # Constrained decoding uses three more program variants: the
            # masked prefill trace, the forced-token chained decode ([B]
            # override vector), and the ambiguous masked decode ([B, V]
            # allowed mask — step 1 below returns TWO ids so it actually
            # traces).  The first tool call would otherwise compile them
            # on the scheduler thread, stalling every in-flight stream.
            e.submit(GenRequest(
                request_id=f"__warmup_con_{n}",
                prompt_ids=[3] * 4, max_new_tokens=3,
                logits_mask_fn=lambda out: (
                    [3] if len(out) == 0 else
                    [3, 4] if len(out) == 1 else None
                ),
            ))
            e.run_to_completion()
            # speculative verify program (KAFKA_TPU_SPECULATIVE_K > 0):
            # organic engagement depends on generated repetition, so the
            # engine compiles it via an all-masked dispatch (no-op at K=0)
            e.warmup_verify()
            # on-device grammar FSM programs (KAFKA_TPU_GRAMMAR_ONDEVICE):
            # compile the fsm decode/verify variants against the
            # builtin-tools + idle grammar — the schema the agent path
            # constrains to in the common (no-MCP) deployment, so the
            # first forced tool call pays serving latency, not an XLA
            # compile on the scheduler thread.  A deployment whose merged
            # MCP registry differs registers its grammar at request time
            # (one retrace if the padded table shape grows).
            if _warmup_grammar is not None:
                e.warmup_grammar(_warmup_grammar)
            # tiered-KV ship programs (KAFKA_TPU_KV_HOST_TIER_MB > 0):
            # compile the per-bucket gather/scatter transfers so the first
            # demotion/promotion pays copy latency, not an XLA compile on
            # the scheduler thread (no-op when the tier is off)
            e.warmup_kv_tier()
        # cross-replica ship programs (KAFKA_TPU_DP_ROLES): compile the
        # per-bucket gather/scatter pairs across the pool edges so the
        # first prefill-and-hand-off pays copy latency, not an XLA
        # compile on the scheduler thread (no-op without role pools)
        warm_disagg = getattr(engine, "warmup_disagg", None)
        if warm_disagg is not None:
            warm_disagg()
        engine.run_to_completion()
        engine_cfg.max_waiting = _admission_bound
        for e in engines:
            e.metrics = EngineMetrics()
        logger.info("warmup compile done in %.1fs", _time.monotonic() - t0)
    # everything compiled past this point is unexpected work under live
    # traffic: the observatory's storm detector only counts this phase
    compile_log.set_phase("first_traffic")
    vision_params = None
    if model_cfg.vision is not None:
        # vision tower (models/vision.py).  Random-init like the text
        # params when no checkpoint supplies one; a Llava checkpoint's
        # tower would load here through the same seam.
        from ..models.vision import vision_init_params

        vision_params = vision_init_params(
            model_cfg.vision, model_cfg.hidden_size, jax.random.PRNGKey(7),
            dtype=model_cfg.activation_dtype,
        )
    provider = TPULLMProvider(
        engine, tokenizer, model_name=cfg.model_name,
        vision_params=vision_params,
    )
    # the startup plan (actual model_cfg, live-device HBM) rides along so
    # /health reports the numbers this deployment was validated against
    provider.memory_plan = memory_plan
    return provider


def default_builtin_tools(cfg: ServingConfig) -> List[Tool]:
    from ..server_tools import builtin_tools

    return builtin_tools(sandbox_url=cfg.local_sandbox_url)


async def create_app(
    cfg: Optional[ServingConfig] = None,
    llm_provider: Optional[LLMProvider] = None,
    db: Optional[DBClient] = None,
    tools: Optional[List[Tool]] = None,
    mcp_servers: Optional[List[MCPServerConfig]] = None,
) -> web.Application:
    """Build the application; DI parameters override config-driven wiring
    (the testing seams the reference got from its ABC layering)."""
    cfg = cfg or ServingConfig.from_env()
    # late env injection (KAFKA_TPU_FAILPOINTS set after import): arm any
    # configured failpoints before the engine builds
    from ..runtime.failpoints import load_env as _load_failpoints

    _load_failpoints()
    # tracing/slow-log config is per-deployment (ServingConfig), applied
    # before the engine builds so every request is eligible from boot
    tracing.configure(
        sample=cfg.trace_sample,
        ring=cfg.trace_ring,
        slow_ttft_ms=cfg.slow_ttft_ms or 0,
        slow_total_ms=cfg.slow_total_ms or 0,
    )
    # SLO targets likewise: configured before the engine builds so every
    # EngineMetrics (including the post-warmup resets) classifies against
    # the deployment's targets.  ALWAYS called — None clears any previous
    # app build's override back to env/default, so two deployments in one
    # process cannot leak targets into each other (runtime/metrics.py).
    from ..runtime.metrics import configure_slo

    configure_slo(ttft_ms=cfg.slo_ttft_ms, tpot_ms=cfg.slo_tpot_ms)
    if llm_provider is None:
        llm_provider = build_tpu_provider(cfg)
    if db is None:
        # remote (PostgREST/Supabase) when KAFKA_TPU_REMOTE_DB_URL is set
        db = make_db_client(cfg.db_path)
    await db.initialize()
    if tools is None:
        try:
            tools = default_builtin_tools(cfg)
        except Exception as e:  # server_tools are optional at boot
            logger.warning("builtin tools unavailable: %s", e)
            tools = []
    if mcp_servers is None:
        # reference server_tools/mcp_servers.py:8-13; override with
        # KAFKA_TPU_MCP_SERVERS (JSON list, '[]' disables). Unreachable
        # servers are skipped with a warning at connect time.
        from ..server_tools.mcp_servers import default_mcp_servers

        mcp_servers = default_mcp_servers()

    kafka = KafkaV1Provider(
        llm_provider,
        thread_db=db,
        tools=tools,
        mcp_servers=mcp_servers,
        default_model=cfg.model_name,
        system_prompt=cfg.system_prompt,
    )
    await kafka.initialize()

    app = web.Application(middlewares=[
        cors_middleware(cfg.cors_origins),
        tracing_middleware(),
        auth_middleware(cfg.api_token),
    ])
    state = {
        "cfg": cfg,
        "db": db,
        "llm": llm_provider,
        "tools": tools,
        "mcp_servers": list(mcp_servers or []),
        "kafka": kafka,
        "draining": False,
        "autoscaler": None,
    }
    app[STATE_KEY] = state
    # Autoscaler control loop (ISSUE 13, README "Autoscaler"): built only
    # when KAFKA_TPU_AUTOSCALE asks for it AND the provider emits the
    # signals contract — the off default constructs NOTHING, so every
    # serving path stays byte-identical to a controller-less build.  The
    # thread starts on the running loop (on_startup) because act-mode
    # resizes schedule provider.resize_dp onto it.
    from ..runtime.autoscaler import MODE_OFF, parse_mode

    if (parse_mode(cfg.autoscale) != MODE_OFF
            and getattr(llm_provider, "signals", None) is not None):
        from ..runtime.autoscaler import (
            AutoscalerConfig,
            AutoscalerController,
        )

        scaler = AutoscalerController(
            llm_provider,
            AutoscalerConfig.from_env(mode=parse_mode(cfg.autoscale)),
            is_draining=lambda: bool(state.get("draining")),
        )
        state["autoscaler"] = scaler

        async def _start_autoscaler(app: web.Application) -> None:
            import asyncio as _asyncio

            scaler.start(loop=_asyncio.get_running_loop())

        app.on_startup.append(_start_autoscaler)
    _add_routes(app)
    app.on_shutdown.append(_drain_on_shutdown)
    app.on_cleanup.append(_cleanup)
    return app


async def _drain_on_shutdown(app: web.Application) -> None:
    """Graceful drain: stop admitting, let in-flight streams finish.

    Runs while connections are still open (aiohttp on_shutdown).  /health
    flips to 503 "draining" so load balancers pull the instance, the
    admission gate rejects new serving requests with 503, and the engine
    gets ServingConfig.drain_timeout_s to finish what it holds before the
    leftovers are cancelled (each still receives its terminal event).
    """
    state = app[STATE_KEY]
    if state.get("draining"):
        return
    state["draining"] = True
    drain = getattr(state["llm"], "drain", None)
    if drain is None:
        return
    timeout = state["cfg"].drain_timeout_s
    logger.info("draining: waiting up to %.1fs for in-flight requests",
                timeout)
    clean = await drain(timeout)
    logger.info("drain %s", "complete" if clean else "timed out (cancelled "
                "remaining requests)")


async def _cleanup(app: web.Application) -> None:
    state = app[STATE_KEY]
    scaler = state.get("autoscaler")
    if scaler is not None:
        # before the provider closes: a poll racing teardown would read
        # a dying engine, and stop() also climbs any applied ladder
        # rungs.  In an executor because stop() joins a thread that may
        # be blocked on a resize_dp coroutine scheduled onto THIS loop —
        # joining inline would deadlock the loop against its own resize
        import asyncio as _asyncio

        await _asyncio.get_running_loop().run_in_executor(
            None, scaler.stop
        )
    await state["kafka"].cleanup()
    await state["db"].close()
    await state["llm"].aclose()


def cors_middleware(origins: str):
    @web.middleware
    async def mw(request: web.Request, handler):
        if request.method == "OPTIONS":
            resp: web.StreamResponse = web.Response(status=204)
        else:
            try:
                resp = await handler(request)
            except web.HTTPException as e:
                # error responses need CORS headers too, or browsers hide
                # the 400/404 body behind a CORS failure
                resp = e
        resp.headers["Access-Control-Allow-Origin"] = origins
        resp.headers["Access-Control-Allow-Methods"] = "GET,POST,PUT,DELETE,OPTIONS"
        resp.headers["Access-Control-Allow-Headers"] = "Content-Type,Authorization"
        if isinstance(resp, web.HTTPException):
            raise resp
        return resp

    return mw


# paths that never start a trace: health probes and the observability
# surface itself (incl. the autoscaler's ~1 Hz signal scrape) would
# otherwise churn the ring with noise
_TRACE_SKIP = ("/health", "/metrics", "/playground", "/debug",
               "/admin/signals", "/admin/autoscaler")


def _incoming_trace(request: web.Request):
    """Adopt an incoming trace identity: X-Request-Id (the id verbatim) or
    a W3C traceparent (00-<32hex trace>-<16hex span>-<flags> — the trace id
    is adopted and the caller's span becomes the root's parent)."""
    rid = request.headers.get("X-Request-Id", "").strip()
    if rid:
        return rid[:128], None
    tp = request.headers.get("traceparent", "").strip()
    parts = tp.split("-")
    if len(parts) == 4 and len(parts[1]) == 32 and len(parts[2]) == 16:
        return parts[1], parts[2]
    return None, None


def tracing_middleware():
    """Root-span middleware: every serving request gets (or adopts) a
    trace id; the whole handler — auth, agent loop, SSE stream — runs
    inside the http.request span.  Sampled-out requests pass through
    untouched (tracing.start_trace returns None)."""

    @web.middleware
    async def mw(request: web.Request, handler):
        if request.method == "OPTIONS" or request.path.startswith(
            _TRACE_SKIP
        ):
            return await handler(request)
        trace_id, parent_id = _incoming_trace(request)
        root = tracing.start_trace(
            request_id=trace_id,
            trace_id=trace_id,
            parent_id=parent_id,
            name="http.request",
            attrs={"method": request.method, "path": request.path},
        )
        if root is None:
            return await handler(request)
        ctx = tracing.current()
        status = None
        try:
            resp = await handler(request)
            status = resp.status
            if not resp.prepared and ctx is not None:
                # streamed responses are already on the wire; buffered
                # ones tell the client which id to ask /debug/trace for
                resp.headers["X-Request-Id"] = ctx.trace_id
            return resp
        except web.HTTPException as e:
            status = e.status
            raise
        finally:
            tracing.finish_trace(root, status=status)

    return mw


def auth_middleware(api_token: Optional[str]):
    """Two bearer tiers: the static machine token (ServingConfig.api_token)
    and per-user SESSION tokens from /v1/auth/login (`sess_…`, stored in
    the DB tier — db/base.py user-store contract; reference: Supabase
    email sessions, playground/src/components/auth-provider.tsx:19-40).

    A valid session resolves request["user_id"] (thread ownership scoping)
    and also satisfies the api_token gate — humans log in, machines carry
    the static token.  An invalid/expired session 401s even on an
    otherwise-open server: a client that presents credentials must not be
    silently downgraded to anonymous.  /health, /playground and /v1/auth/
    login stay open; SIGNUP runs under the api_token gate when one is
    configured (an open signup would mint sessions that bypass the static
    token — accounts on a closed instance are operator-provisioned, the
    invite model).  No api_token configured = anonymous access allowed,
    the reference's local-dev default.
    """
    open_paths = ("/health", "/playground", "/v1/auth/login")

    @web.middleware
    async def mw(request: web.Request, handler):
        if request.path in open_paths:
            return await handler(request)
        supplied = request.headers.get("Authorization", "")
        if supplied.startswith("Bearer sess_"):
            token = supplied[len("Bearer "):]
            try:
                user_id = await _state(request)["db"].get_session_user(token)
            except NotImplementedError:
                user_id = None
            if user_id is None:
                return web.json_response(
                    {"error": {"message": "invalid or expired session",
                               "type": "authentication_error"}},
                    status=401,
                )
            request["user_id"] = user_id
            return await handler(request)
        if api_token:
            # compare as bytes: compare_digest raises TypeError on non-ASCII
            # str inputs, which would turn a malformed credential into a 500
            if not hmac.compare_digest(
                supplied.encode("utf-8", "surrogateescape"),
                f"Bearer {api_token}".encode(),
            ):
                return web.json_response(
                    {"error": {"message": "invalid or missing bearer token",
                               "type": "authentication_error"}},
                    status=401,
                )
        return await handler(request)

    return mw


def _add_routes(app: web.Application) -> None:
    r = app.router
    r.add_post("/v1/chat/completions", chat_completions)
    r.add_post("/v1/threads/{thread_id}/chat/completions", thread_chat_completions)
    r.add_post("/v1/agent/run", agent_run)
    r.add_post("/v1/threads/{thread_id}/agent/run", thread_agent_run)
    r.add_post("/v1/threads", create_thread)
    r.add_get("/v1/threads", list_threads)
    r.add_get("/v1/threads/{thread_id}", get_thread)
    r.add_get("/v1/threads/{thread_id}/messages", get_thread_messages)
    r.add_delete("/v1/threads/{thread_id}", delete_thread)
    r.add_delete("/v1/threads/{thread_id}/messages", delete_thread_messages)
    r.add_put("/v1/threads/{thread_id}/config", set_thread_config)
    r.add_get("/v1/profiles", list_profiles)
    r.add_post("/v1/profiles", create_profile)
    r.add_get("/v1/models", list_models)
    r.add_post("/v1/auth/signup", auth_signup)
    r.add_post("/v1/auth/login", auth_login)
    r.add_get("/health", health)
    r.add_get("/metrics", metrics)
    r.add_get("/admin/signals", admin_signals)
    r.add_get("/admin/autoscaler", admin_autoscaler)
    r.add_post("/admin/resize", resize_topology)
    r.add_post("/admin/drain/{replica}", admin_drain_replica)
    r.add_post("/debug/profile", capture_profile)
    r.add_get("/debug/traces", debug_traces)
    r.add_get("/debug/trace/{request_id}", debug_trace)
    r.add_get("/debug/flight/{replica}", debug_flight)
    r.add_get("/debug/compiles", debug_compiles)
    r.add_get("/debug/kernels", debug_kernels)
    r.add_get("/playground", playground)
    # OPTIONS preflight is answered by cors_middleware before routing


def _state(request: web.Request) -> dict:
    return request.app[STATE_KEY]


async def _parse(request: web.Request, model_cls):
    try:
        return model_cls.model_validate(await request.json())
    except ValidationError as e:
        raise web.HTTPBadRequest(
            text=e.json(), content_type="application/json"
        )
    except Exception:
        raise web.HTTPBadRequest(text='{"error": "invalid JSON body"}',
                                 content_type="application/json")


def _admission_gate(request: web.Request) -> None:
    """Reject serving requests when draining or when the engine's waiting
    queue is full (HTTP 503 / 429 + Retry-After).  Thread CRUD and health
    stay open — only endpoints that would submit engine work are gated."""
    state = _state(request)
    if state.get("draining"):
        raise web.HTTPServiceUnavailable(
            text=json.dumps({"error": {
                "message": "server is draining for shutdown",
                "type": "server_draining",
            }}),
            content_type="application/json",
            headers={"Retry-After": str(int(
                state["cfg"].drain_timeout_s
                if hasattr(state["cfg"], "drain_timeout_s") else 30
            ))},
        )
    check = getattr(state["llm"], "admission_check", None)
    if check is None:
        return
    retry_after = check()
    if retry_after is None:
        return
    record = getattr(state["llm"], "record_rejection", None)
    if record is not None:
        record()
    raise web.HTTPTooManyRequests(
        text=json.dumps({"error": {
            "message": "request queue is full; retry later "
                       "(server_overloaded)",
            "type": "server_overloaded",
        }}),
        content_type="application/json",
        headers={"Retry-After": str(max(1, int(retry_after)))},
    )


# ---------------------------------------------------------------------------
# event-stream plumbing shared by the four serving endpoints
# ---------------------------------------------------------------------------


async def _agent_events(
    request: web.Request,
    req_body,
    thread_id: Optional[str],
) -> AsyncIterator[Dict[str, Any]]:
    """Run the right kafka flavor; yield protocol events + tool_messages."""
    state = _state(request)
    sampling = dict(
        temperature=req_body.temperature if req_body.temperature is not None else 0.7,
        max_tokens=req_body.max_tokens,
    )
    if getattr(req_body, "tool_choice", None) is not None:
        sampling["tool_choice"] = req_body.tool_choice
    messages = [m.model_dump(exclude_none=True) for m in req_body.messages]
    model = req_body.model or state["cfg"].model_name
    acc = MessageAccumulator()

    if thread_id is None:
        kafka = state["kafka"]
        stream = kafka.run(messages, model=model, **sampling)
    else:
        # per-thread provider: thread config (global_prompt/playbooks/model)
        # is fetched at initialize (reference server.py:237-245)
        kafka = KafkaV1Provider(
            state["llm"],
            thread_db=state["db"],
            tools=state["tools"],
            mcp_servers=state["mcp_servers"],
            thread_id=thread_id,
            default_model=model,
            system_prompt=state["cfg"].system_prompt,
        )
        await kafka.initialize()
        stream = kafka.run_with_thread(thread_id, messages, **sampling)

    # tool_messages batching (reference server.py:330-335, adapted): the
    # CUMULATIVE tool-cycle history is re-batched before each new
    # completion's chunks (and before agent_done) whenever it has grown —
    # cumulative because the playground contract client REPLACES all its
    # tool/tool-call messages with each batch (page.tsx:195-215), so a
    # per-cycle batch would wipe earlier cycles from the transcript.
    # Plain assistant text is never batched — it streams live (our
    # improvement over the reference's re-streaming) and batching it would
    # duplicate it client-side.  All covered by tests/test_sse_contract.py.
    last_batched = None

    def _cumulative_batch():
        return [
            m.to_dict() for m in acc.messages
            if m.role == "tool" or m.tool_calls
        ]

    def _maybe_batch():
        # Re-emit whenever the canonical batch CONTENT changed, not just its
        # count — server-side sanitization can rewrite a message in place
        # (e.g. truncation differing from the streamed deltas), and the
        # client must end up holding the durable canonical form.
        nonlocal last_batched
        batch = _cumulative_batch()
        # constant-size digest (a Python hash() collision after an in-place
        # rewrite would silently skip the corrected canonical batch; the
        # raw JSON string would pin the whole batch in memory per stream)
        fingerprint = hashlib.sha256(
            json.dumps(batch, sort_keys=True, default=str).encode()
        ).hexdigest()
        if batch and fingerprint != last_batched:
            last_batched = fingerprint
            return {"type": "tool_messages", "messages": batch}
        return None

    last_cid = None
    try:
        async for event in stream:
            if event.get("object") == "chat.completion.chunk":
                # the batch can only grow between completions: check on the
                # first chunk of each new completion, not per token
                cid = event.get("id")
                if cid != last_cid:
                    last_cid = cid
                    batch_ev = _maybe_batch()
                    if batch_ev:
                        yield batch_ev
            acc.add_event(event)
            if event.get("type") == "agent_done":
                batch_ev = _maybe_batch()
                if batch_ev:
                    yield batch_ev
            yield event
    finally:
        if thread_id is not None:
            await kafka.cleanup()


async def _collect_completion(
    events: AsyncIterator[Dict[str, Any]], model: str
) -> Dict[str, Any]:
    """Drain an event stream into a non-streaming chat completion."""
    acc = MessageAccumulator()
    usage = Usage()
    async for event in events:
        acc.add_event(event)
        if event.get("object") == "chat.completion.chunk" and event.get("usage"):
            u = event["usage"]
            usage.prompt_tokens += u.get("prompt_tokens", 0)
            usage.completion_tokens += u.get("completion_tokens", 0)
            usage.total_tokens += u.get("total_tokens", 0)
            usage.cached_prompt_tokens += (
                u.get("prompt_tokens_details") or {}
            ).get("cached_tokens", 0)
    final = acc.final_content
    return {
        "id": new_completion_id(),
        "object": "chat.completion",
        "created": 0,
        "model": model,
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": final},
                "finish_reason": "stop",
            }
        ],
        "usage": usage.to_dict(),
    }


# ---------------------------------------------------------------------------
# serving endpoints
# ---------------------------------------------------------------------------


async def _completion_response(events, model: str) -> web.Response:
    """Non-streaming completion with OpenAI-style structured errors."""
    try:
        return web.json_response(await _collect_completion(events, model))
    except ServerOverloadedError as e:
        # engine-thread admission backstop: same 429 contract as the gate
        # (type server_overloaded + Retry-After), not a generic 4xx
        return web.json_response(
            {"error": {"message": str(e), "type": "server_overloaded"}},
            status=429,
            headers={"Retry-After": str(max(1, int(e.retry_after_s)))},
        )
    except LLMProviderError as e:
        status = e.status_code or 500
        return web.json_response(
            {
                "error": {
                    "message": str(e),
                    "type": "invalid_request_error"
                    if status < 500 else "server_error",
                    "code": "context_length_exceeded"
                    if isinstance(e, ContextLengthError) else None,
                }
            },
            status=status,
        )


async def chat_completions(request: web.Request) -> web.StreamResponse:
    _admission_gate(request)
    body = await _parse(request, ChatCompletionRequest)
    events = _agent_events(request, body, thread_id=None)
    if body.stream:
        return await sse_response(request, events)
    return await _completion_response(events, body.model)


async def thread_chat_completions(request: web.Request) -> web.StreamResponse:
    _admission_gate(request)
    thread_id = request.match_info["thread_id"]
    await _check_thread_owner(request, thread_id, create=True)
    body = await _parse(request, ChatCompletionRequest)
    events = _agent_events(request, body, thread_id=thread_id)
    if body.stream:
        return await sse_response(request, events)
    return await _completion_response(events, body.model)


async def agent_run(request: web.Request) -> web.StreamResponse:
    _admission_gate(request)
    body = await _parse(request, AgentRunRequest)
    return await sse_response(
        request, _agent_events(request, body, thread_id=None)
    )


async def thread_agent_run(request: web.Request) -> web.StreamResponse:
    _admission_gate(request)
    thread_id = request.match_info["thread_id"]
    await _check_thread_owner(request, thread_id, create=True)
    body = await _parse(request, AgentRunRequest)
    return await sse_response(
        request, _agent_events(request, body, thread_id=thread_id)
    )


# ---------------------------------------------------------------------------
# thread CRUD
# ---------------------------------------------------------------------------


async def create_thread(request: web.Request) -> web.Response:
    db = _state(request)["db"]
    body = {}
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:
            body = {}
    # profile inheritance (reference: threads join kafka_profiles for
    # global_prompt/model config, supabase.py:458-541): a thread created
    # with profile_id copies that profile's config as its own.  Validated
    # BEFORE creating the thread — a 400 must not leave an orphan row.
    pid = body.get("profile_id")
    profile = None
    if pid:
        get_profile = getattr(db, "get_profile", None)
        profile = await get_profile(pid) if get_profile else None
        if profile is None:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": f"unknown profile {pid!r}"}),
                content_type="application/json",
            )
    tid = await db.create_thread(
        thread_id=body.get("thread_id"), metadata=body.get("metadata")
    )
    if request.get("user_id") is not None:
        try:
            await db.set_thread_owner(tid, request["user_id"])
        except NotImplementedError:
            pass
    if profile is not None:
        await db.set_thread_config(
            tid, {**profile["config"], "profile_id": pid}
        )
    meta = await db.get_thread_metadata(tid)
    return web.json_response(meta, status=201)


async def list_profiles(request: web.Request) -> web.Response:
    db = _state(request)["db"]
    fn = getattr(db, "list_profiles", None)
    if fn is None:
        raise web.HTTPNotImplemented(
            text='{"error": "profiles unsupported by this DB backend"}',
            content_type="application/json",
        )
    return web.json_response({"profiles": await fn()})


async def create_profile(request: web.Request) -> web.Response:
    db = _state(request)["db"]
    fn = getattr(db, "create_profile", None)
    if fn is None:
        raise web.HTTPNotImplemented(
            text='{"error": "profiles unsupported by this DB backend"}',
            content_type="application/json",
        )
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(
            text='{"error": "invalid JSON body"}',
            content_type="application/json",
        )
    name = body.get("name")
    if not name:
        raise web.HTTPBadRequest(
            text='{"error": "profile name required"}',
            content_type="application/json",
        )
    profile = await fn(name, config=body.get("config") or {})
    return web.json_response(profile, status=201)


async def list_threads(request: web.Request) -> web.Response:
    db = _state(request)["db"]
    user = request.get("user_id")
    if user is not None:
        # per-user sidebar scope (reference: sidebar.tsx:40-80 filters by
        # the Supabase session user)
        return web.json_response(
            {"threads": await db.list_threads_for_user(user)}
        )
    try:
        # anonymous requests see only unowned threads
        threads = await db.list_threads_unowned()
    except NotImplementedError:  # backend without a user store: all open
        threads = await db.list_threads()
    return web.json_response({"threads": threads})


async def _check_thread_owner(request: web.Request, tid: str,
                              create: bool = False) -> None:
    """Enforce/establish thread ownership for session users.

    Another user's thread answers 404 (existence is not leaked — the
    reference's per-user Supabase listing has the same property).  A
    session user touching an unowned-or-new thread claims it; anonymous
    requests see only unowned threads.  DB clients without a user store
    skip enforcement entirely (the pre-auth behavior).
    """
    db = _state(request)["db"]
    user = request.get("user_id")
    try:
        owner = await db.get_thread_owner(tid)
    except NotImplementedError:
        return
    if owner is not None and owner != user:
        raise web.HTTPNotFound(
            text=f'{{"error": "thread {tid} not found"}}',
            content_type="application/json",
        )
    # claiming happens only on WRITE paths (create=True: chat/agent run) —
    # a mere GET of an unowned thread must not transfer its ownership away
    # from the anonymous client that created it
    if create and user is not None and owner is None:
        if not await db.thread_exists(tid):
            await db.create_thread(tid)
        await db.set_thread_owner(tid, user)


async def _require_thread(request: web.Request) -> str:
    db = _state(request)["db"]
    tid = request.match_info["thread_id"]
    if not await db.thread_exists(tid):
        raise web.HTTPNotFound(
            text=f'{{"error": "thread {tid} not found"}}',
            content_type="application/json",
        )
    await _check_thread_owner(request, tid)
    return tid


async def get_thread(request: web.Request) -> web.Response:
    tid = await _require_thread(request)
    return web.json_response(await _state(request)["db"].get_thread_metadata(tid))


async def get_thread_messages(request: web.Request) -> web.Response:
    tid = await _require_thread(request)
    msgs = await _state(request)["db"].get_thread_messages(tid)
    return web.json_response({"thread_id": tid, "messages": msgs})


async def delete_thread(request: web.Request) -> web.Response:
    tid = await _require_thread(request)
    await _state(request)["db"].delete_thread(tid)
    return web.json_response({"deleted": tid})


async def delete_thread_messages(request: web.Request) -> web.Response:
    tid = await _require_thread(request)
    await _state(request)["db"].delete_thread_messages(tid)
    return web.json_response({"cleared": tid})


async def set_thread_config(request: web.Request) -> web.Response:
    tid = await _require_thread(request)
    db = _state(request)["db"]
    cfg = await request.json()
    await db.set_thread_config(tid, cfg)
    return web.json_response({"thread_id": tid, "config": cfg})


# ---------------------------------------------------------------------------
# models / health
# ---------------------------------------------------------------------------


async def _session_response(db, user_id: str, email: str) -> web.Response:
    from .auth import new_session_token, session_expiry

    token = new_session_token()
    await db.create_session(user_id, token, session_expiry())
    return web.json_response(
        {"token": token, "user_id": user_id, "email": email}
    )


async def _auth_body(request: web.Request) -> tuple:
    try:
        body = await request.json()
        assert isinstance(body, dict)
    except Exception:
        raise web.HTTPBadRequest(
            text='{"error": "invalid JSON body"}',
            content_type="application/json",
        )
    return ((body.get("email") or "").strip().lower(),
            body.get("password") or "")


async def auth_signup(request: web.Request) -> web.Response:
    """Create a user + open a session (reference: Supabase email signup)."""
    import asyncio as _asyncio

    from .auth import hash_password, new_salt

    db = _state(request)["db"]
    email, password = await _auth_body(request)
    if "@" not in email or len(password) < 6:
        raise web.HTTPBadRequest(
            text='{"error": "need a valid email and a password of 6+ chars"}',
            content_type="application/json",
        )
    salt = new_salt()
    # scrypt is ~50ms of CPU: off the event loop, or every in-flight SSE
    # stream hiccups for the duration
    pw_hash = await _asyncio.to_thread(hash_password, password, salt)
    try:
        user_id = await db.create_user(email, pw_hash, salt)
    except ValueError:
        return web.json_response(
            {"error": {"message": "email already registered",
                       "type": "invalid_request_error"}},
            status=409,
        )
    except NotImplementedError:
        raise web.HTTPNotImplemented(
            text='{"error": "this DB backend has no user store"}',
            content_type="application/json",
        )
    return await _session_response(db, user_id, email)


async def auth_login(request: web.Request) -> web.Response:
    import asyncio as _asyncio

    from .auth import verify_password

    db = _state(request)["db"]
    email, password = await _auth_body(request)
    try:
        user = await db.get_user_by_email(email)
    except NotImplementedError:
        raise web.HTTPNotImplemented(
            text='{"error": "this DB backend has no user store"}',
            content_type="application/json",
        )
    if user is None or not await _asyncio.to_thread(
        verify_password, password, user["salt"], user["password_hash"]
    ):
        return web.json_response(
            {"error": {"message": "invalid email or password",
                       "type": "authentication_error"}},
            status=401,
        )
    return await _session_response(db, user["user_id"], user["email"])


async def list_models(request: web.Request) -> web.Response:
    llm = _state(request)["llm"]
    return web.json_response(
        {"object": "list", "data": llm.get_available_models()}
    )


async def health(request: web.Request) -> web.Response:
    state = _state(request)
    llm = state["llm"]
    draining = bool(state.get("draining"))
    payload: Dict[str, Any] = {
        # "draining" + 503 pulls the instance from load-balancer rotation
        # while in-flight streams finish (graceful-drain contract)
        "status": "draining" if draining else "ok",
        "kafka_initialized": state["kafka"]._initialized,
    }
    plan = getattr(llm, "memory_plan", None)  # set by build_tpu_provider
    if plan is not None:
        payload["memory_plan"] = plan.summary()
    engine = getattr(llm, "engine", None)
    if engine is not None:
        # DataParallelEngines exposes .engines; a single engine is its own
        # one-element "replica set" so the page math below is uniform
        replicas = getattr(engine, "engines", [engine])
        payload["engine"] = {
            "active": engine.num_active,
            "waiting": len(engine.waiting),
            "free_pages": sum(e.pool.free_pages for e in replicas),
            "total_pages": sum(e.pool.num_pages for e in replicas),
        }
        if len(replicas) > 1:
            payload["engine"]["dp"] = len(replicas)
        health_records = getattr(engine, "health", None)
        if health_records:
            # replica supervision at a glance: a load balancer (or a
            # human) sees which replicas are quarantined without parsing
            # the full /metrics snapshot
            payload["engine"]["replicas"] = [h.state for h in health_records]
    return web.json_response(payload, status=503 if draining else 200)


async def metrics(request: web.Request) -> web.Response:
    """Serving counters (SURVEY §5.1/5.5): TTFT/TPOT percentiles, token
    throughput, batch occupancy, pages in use, prefix-cache reuse.  These
    are the numbers bench.py reports — one source of truth."""
    llm = _state(request)["llm"]
    engine = getattr(llm, "engine", None)
    if engine is None:
        return web.json_response({"error": "no local engine"}, status=404)
    snap = engine.metrics.snapshot(engine)
    # sandbox subprocess supervision counters (crashes, supervised
    # restarts, crash loops, reaped zombie handles) — module-aggregated
    # across factories, same one-source-of-truth rule as the engine
    # counters
    from ..sandbox.process import supervisor_snapshot

    snap["sandbox"] = supervisor_snapshot()
    # tracing counters + the slow-request counter (requests over the
    # configured TTFT/total thresholds) join the same snapshot
    snap["tracing"] = tracing.counters()
    if isinstance(snap.get("requests"), dict):
        snap["requests"]["slow"] = tracing.slow_count()
    # autoscaler control-loop counters (AUTOSCALER_METRIC_KEYS): one
    # controller per process, merged here like the sandbox/tracing
    # sections (absent when KAFKA_TPU_AUTOSCALE is off)
    scaler = _state(request).get("autoscaler")
    if scaler is not None:
        snap["autoscaler"] = scaler.metrics_section()
    # compile observatory counters (COMPILE_METRIC_KEYS): process-wide
    # like the sandbox/autoscaler sections — XLA compiles are per-process
    # events, not per-replica (absent when KAFKA_TPU_COMPILE_RING=0)
    from ..runtime import compile_log

    obs = compile_log.get()
    if obs is not None:
        snap["compiles"] = obs.metrics_section()
    if request.query.get("format") == "prometheus":
        from .prometheus import render_prometheus

        return web.Response(
            text=render_prometheus(snap),
            headers={"Content-Type":
                     "text/plain; version=0.0.4; charset=utf-8"},
        )
    return web.json_response(snap)


async def admin_signals(request: web.Request) -> web.Response:
    """The autoscaler signal feed (ISSUE 10): one coherent JSON snapshot
    of queue depth + trend, batch occupancy, SLO window attainment,
    goodput, and per-replica utilization + quarantine state.

    This endpoint is the documented INPUT CONTRACT for the coming
    /admin/resize control loop (README "SLO telemetry"): a scaler reads
    it at ~1 Hz and decides dp from attainment_1m, queue trend, and
    per-kind MFU/HBM headroom.  Read-only — unlike /admin/resize it
    works without a configured API token (same policy as /metrics), and
    honors the bearer gate when one is set."""
    state = _state(request)
    llm = state["llm"]
    signals = getattr(llm, "signals", None)
    if signals is None or getattr(llm, "engine", None) is None:
        return web.json_response(
            {"error": "no local engine (this deployment emits no "
                      "autoscaler signals)"},
            status=404,
        )
    payload = signals()
    # serving-state bits only the app layer knows
    payload["draining"] = bool(state.get("draining"))
    payload["admission"] = {
        "max_queue_depth": state["cfg"].max_queue_depth,
    }
    return web.json_response(payload)


async def admin_autoscaler(request: web.Request) -> web.Response:
    """The autoscaler control loop's bounded decision log + live state
    (ISSUE 13, README "Autoscaler"): mode, config, degradation-ladder
    rung, cooldowns, and every recorded decision (cause, condensed
    inputs snapshot, action, vetoes, outcome; consecutive identical
    holds collapse into one counted entry).  Read-only — same token
    policy as /admin/signals (works without a configured token, honors
    the bearer gate when one is set).  404 when KAFKA_TPU_AUTOSCALE is
    off: no controller runs, so there is nothing to report."""
    scaler = _state(request).get("autoscaler")
    if scaler is None:
        return web.json_response(
            {"error": "autoscaler not running (KAFKA_TPU_AUTOSCALE is "
                      "off, or this deployment emits no signals)"},
            status=404,
        )
    return web.json_response(scaler.snapshot())


async def resize_topology(request: web.Request) -> web.Response:
    """Rebuild the DP replica set at a new dp count (replica loss or
    scale-down) while queued requests survive: body {"dp": N, optional
    "drain_timeout_s": S, optional "roles": "prefill:P,decode:D"}.
    Started requests get the drain budget to finish; leftovers are
    cancelled with terminal events (reported as "clean": false).  When
    "roles" is present it re-shapes the prefill/decode pools in the same
    rebuild (validated by the parse_dp_roles rules, P + D == dp; "" or
    null dissolves the pools back to colocated); absent keeps the
    current spec re-derived for the new dp.  Unlike serving endpoints,
    this one is operator-destructive (it cancels whatever cannot
    drain), so the open-if-no-token dev default does NOT apply: without
    a configured KAFKA_TPU_API_TOKEN the endpoint refuses outright."""
    if not _state(request)["cfg"].api_token:
        return web.json_response(
            {"error": "admin endpoints require KAFKA_TPU_API_TOKEN to "
                      "be configured"},
            status=403,
        )
    llm = _state(request)["llm"]
    resize = getattr(llm, "resize_dp", None)
    if resize is None or not hasattr(
        getattr(llm, "engine", None), "rebuild"
    ):
        return web.json_response(
            {"error": "this deployment has no resizable DP topology"},
            status=501,
        )
    try:
        body = await request.json()
        dp = int(body["dp"])
        drain_timeout_s = float(
            body.get("drain_timeout_s",
                     _state(request)["cfg"].drain_timeout_s)
        )
        roles_given = "roles" in body
        roles = body.get("roles")
        if roles_given and roles is not None and not isinstance(roles, str):
            raise TypeError("roles must be a string or null")
    except Exception:
        return web.json_response(
            {"error": 'body must be {"dp": N[, "drain_timeout_s": S]'
                      '[, "roles": "prefill:P,decode:D"|null]}'},
            status=400,
        )
    if dp < 1:
        return web.json_response({"error": "dp must be >= 1"}, status=400)
    kwargs = {"drain_timeout_s": drain_timeout_s}
    if roles_given:
        kwargs["roles"] = roles
    try:
        # rebuild compiles are phased by the provider (_resize_locked
        # sets the observatory to "rebuild" so they don't read as a
        # compile storm) — act-mode autoscaler resizes share that path
        clean = await resize(dp, **kwargs)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    except RuntimeError as e:
        return web.json_response({"error": str(e)}, status=409)
    out = {"dp": dp, "clean": clean}
    if roles_given:
        out["roles"] = roles or None
    return web.json_response(out)


async def admin_drain_replica(request: web.Request) -> web.Response:
    """Flush one replica's warm KV state into the shared object store
    (ISSUE 14): every cached radix run is archived content-addressed and
    every thread's sleep manifest written, so the replica can be removed
    (POST /admin/resize to a smaller dp — "drain-then-shrink", which the
    act-mode autoscaler performs automatically before its scale-ins)
    without discarding any warm conversation: dormant threads wake on
    the survivors with cache_source="object_tier" instead of
    re-prefilling.  Non-destructive — the replica keeps serving
    unchanged if it is kept after all.  Requires the object tier
    (KAFKA_TPU_KV_OBJECT_DIR) and, like /admin/resize, a configured
    KAFKA_TPU_API_TOKEN (it parks the scheduler for the flush)."""
    if not _state(request)["cfg"].api_token:
        return web.json_response(
            {"error": "admin endpoints require KAFKA_TPU_API_TOKEN to "
                      "be configured"},
            status=403,
        )
    llm = _state(request)["llm"]
    drain = getattr(llm, "drain_replica", None)
    if drain is None or getattr(llm, "engine", None) is None:
        return web.json_response(
            {"error": "this deployment has no drainable engine"},
            status=501,
        )
    try:
        idx = int(request.match_info["replica"])
    except ValueError:
        return web.json_response(
            {"error": "replica must be an integer index"}, status=400
        )
    try:
        stats = await drain(idx)
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    except RuntimeError as e:
        return web.json_response({"error": str(e)}, status=409)
    if not stats.get("enabled", True):
        return web.json_response(
            {"error": "object tier not configured "
                      "(set KAFKA_TPU_KV_OBJECT_DIR)", **stats},
            status=409,
        )
    return web.json_response(stats)


async def debug_traces(request: web.Request) -> web.Response:
    """Recent-traces index (newest first): ids, durations, span names —
    enough to find the trace id to pull from /debug/trace/{request_id}."""
    return web.json_response({
        "traces": tracing.recent_traces(),
        "counters": tracing.counters(),
        "sample": tracing.sample_rate(),
    })


async def debug_trace(request: web.Request) -> web.Response:
    """One request's span tree as Chrome trace-event JSON — load the body
    in Perfetto (ui.perfetto.dev) or chrome://tracing.  Keyed by the trace
    id (== the X-Request-Id the request carried or was assigned)."""
    data = tracing.chrome_trace(request.match_info["request_id"])
    if data is None:
        raise web.HTTPNotFound(
            text=json.dumps({"error": "unknown trace (evicted from the "
                             "ring, or the request was sampled out)"}),
            content_type="application/json",
        )
    return web.json_response(data)


async def debug_flight(request: web.Request) -> web.Response:
    """One replica's live flight-recorder ring (ISSUE 11): the per-
    scheduler-iteration decision log, measured dispatch timing, and the
    anomaly detector state.  `scripts/flightview.py` pretty-prints the
    payload; postmortem dumps of the same shape land next to the
    persisted traces on engine failure/quarantine."""
    llm = _state(request)["llm"]
    engine = getattr(llm, "engine", None)
    if engine is None:
        return web.json_response({"error": "no local engine"}, status=404)
    replicas = getattr(engine, "engines", [engine])
    try:
        idx = int(request.match_info["replica"])
    except ValueError:
        return web.json_response(
            {"error": "replica must be an integer index"}, status=400
        )
    if not 0 <= idx < len(replicas):
        return web.json_response(
            {"error": f"replica {idx} out of range (dp={len(replicas)})"},
            status=404,
        )
    flight = getattr(replicas[idx], "flight", None)
    if flight is None:
        return web.json_response(
            {"error": "flight recorder disabled "
                      "(KAFKA_TPU_FLIGHT_RING=0)"},
            status=404,
        )
    payload = flight.snapshot()
    payload["replica"] = idx
    payload["dp"] = len(replicas)
    return web.json_response(payload)


async def debug_compiles(request: web.Request) -> web.Response:
    """The compile observatory's bounded ring (ISSUE 18): every XLA
    compilation this process performed — label, wall seconds, cache
    hit/miss/off, and the serving phase it happened in (boot / warmup /
    first_traffic / rebuild) — plus storm-detector state and running
    totals.  `scripts/flightview.py --compiles` pretty-prints the
    payload.  Read-only, same token policy as /metrics."""
    from ..runtime import compile_log

    obs = compile_log.get()
    if obs is None:
        return web.json_response(
            {"error": "compile observatory disabled "
                      "(KAFKA_TPU_COMPILE_RING=0)"},
            status=404,
        )
    return web.json_response(obs.snapshot())


async def debug_kernels(request: web.Request) -> web.Response:
    """Sampled per-kernel device timing (ISSUE 18): the top-K kernels by
    device time, grouped by the dispatch kinds active in each sampled
    window, from KAFKA_TPU_PROFILE_SAMPLE=N every-Nth-step traces.
    Aggregated across DP replicas (each engine owns its own sampler).
    404 when sampling is off — the steady-state default, where every
    dispatch path is byte-identical to a build without this feature."""
    llm = _state(request)["llm"]
    engine = getattr(llm, "engine", None)
    if engine is None:
        return web.json_response({"error": "no local engine"}, status=404)
    try:
        top_k = int(request.query.get("top_k", "20"))
    except ValueError:
        return web.json_response(
            {"error": "top_k must be an integer"}, status=400
        )
    samplers = [
        (i, s) for i, e in enumerate(getattr(engine, "engines", [engine]))
        if (s := getattr(e, "kernel_sampler", None)) is not None
    ]
    if not samplers:
        return web.json_response(
            {"error": "kernel sampling disabled "
                      "(set KAFKA_TPU_PROFILE_SAMPLE=N)"},
            status=404,
        )
    payload = samplers[0][1].snapshot(top_k=top_k)
    payload["replicas"] = [
        dict(s.snapshot(top_k=top_k), replica=i) for i, s in samplers
    ] if len(samplers) > 1 else None
    if payload["replicas"] is None:
        del payload["replicas"]
    return web.json_response(payload)


async def playground(request: web.Request) -> web.Response:
    """The in-tree chat client (reference: playground/src/, a Next.js app).

    One static file consuming the 4-event SSE protocol with the exact
    reconstruction rules of core/sse_client.py."""
    import os

    path = os.path.join(os.path.dirname(__file__), "playground.html")
    return web.FileResponse(path)


_PROFILE_BUSY = False
_PROFILE_DIR = "/tmp/kafka_tpu_trace"


def _flight_seqs(llm) -> Optional[List[Dict[str, Any]]]:
    """Per-replica flight-recorder sequence cursors (None = no engine or
    recorder off everywhere)."""
    engine = getattr(llm, "engine", None)
    if engine is None:
        return None
    out = []
    for i, e in enumerate(getattr(engine, "engines", [engine])):
        flight = getattr(e, "flight", None)
        if flight is not None:
            out.append({"replica": i, "seq": flight.next_seq})
    return out or None


async def capture_profile(request: web.Request) -> web.Response:
    """Capture a jax.profiler device trace (xplane) for offline analysis.

    Body: {"seconds": 2}.  The trace (written under /tmp/kafka_tpu_trace —
    server-chosen, not client-chosen) covers whatever the engine executes
    during the window — point a load at the server first.  Gated behind
    KAFKA_TPU_PROFILING=1 (trace files can contain workload detail); one
    capture at a time.

    When an API token is configured, this endpoint requires the MACHINE
    token specifically — a per-user session that satisfies the general
    bearer middleware does not qualify (ISSUE 11 satellite: profile
    captures expose workload detail and eat device time; they are an
    operator surface like /admin/resize, not a user one).

    The response includes the flight-recorder window covering the
    capture (per-replica [start_seq, end_seq) plus wall timestamps), so
    xplane slices correlate with the scheduler's per-iteration decision
    records at GET /debug/flight/{replica}."""
    import os
    import time as _time

    if os.environ.get("KAFKA_TPU_PROFILING", "0") not in ("1", "true"):
        return web.json_response(
            {"error": "profiling disabled (set KAFKA_TPU_PROFILING=1)"},
            status=403,
        )
    cfg = _state(request)["cfg"]
    if cfg.api_token:
        supplied = request.headers.get("Authorization", "")
        if not hmac.compare_digest(
            supplied.encode("utf-8", "surrogateescape"),
            f"Bearer {cfg.api_token}".encode(),
        ):
            return web.json_response(
                {"error": {"message": "profile capture requires the "
                           "configured API token",
                           "type": "authentication_error"}},
                status=401,
            )
    global _PROFILE_BUSY
    # check-and-set with no await in between: concurrent requests must not
    # race past the guard (asyncio is single-threaded, so this is atomic)
    if _PROFILE_BUSY:
        return web.json_response(
            {"error": "a profile capture is already running"}, status=409
        )
    _PROFILE_BUSY = True
    try:
        import asyncio

        import jax

        try:
            body = await request.json()
        except Exception:
            body = {}
        try:
            seconds = float(body.get("seconds", 2.0))
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "'seconds' must be a number"}, status=400
            )
        if not (0.1 <= seconds <= 30.0):
            return web.json_response(
                {"error": "'seconds' must be in [0.1, 30]"}, status=400
            )
        llm = _state(request)["llm"]
        start_seqs = _flight_seqs(llm)
        # the process-wide trace lock is shared with the every-Nth-step
        # kernel sampler (runtime/kernel_profiler.py): jax.profiler
        # supports one trace at a time, so an open sampler window must
        # make this capture back off rather than crash the scheduler
        from ..runtime import kernel_profiler

        if not kernel_profiler.try_acquire_trace():
            return web.json_response(
                {"error": "device tracing busy (kernel sampler window "
                          "open, or another capture running)"},
                status=409,
            )
        t_start = _time.time()
        try:
            jax.profiler.start_trace(_PROFILE_DIR)
            try:
                await asyncio.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        finally:
            kernel_profiler.release_trace()
        t_end = _time.time()
        end_seqs = _flight_seqs(llm)
    finally:
        _PROFILE_BUSY = False
    flight_window = None
    if start_seqs is not None and end_seqs is not None:
        ends = {e["replica"]: e["seq"] for e in end_seqs}
        flight_window = {
            "t_start": round(t_start, 4),
            "t_end": round(t_end, 4),
            "replicas": [
                {"replica": s["replica"], "start_seq": s["seq"],
                 "end_seq": ends.get(s["replica"], s["seq"])}
                for s in start_seqs
            ],
        }
    return web.json_response({
        "trace_dir": _PROFILE_DIR,
        "seconds": seconds,
        # correlate xplane slices with scheduler decisions: fetch
        # /debug/flight/{replica} and select records with
        # start_seq <= seq < end_seq (or t in [t_start, t_end])
        "flight_window": flight_window,
    })


def run_server(cfg: Optional[ServingConfig] = None) -> None:
    cfg = cfg or ServingConfig.from_env()
    from ..logs import setup_logging

    # KAFKA_TPU_LOG_FORMAT=json (or cfg.log_format): every record carries
    # trace_id/span_id/thread_id for cross-process correlation
    setup_logging(cfg.log_format)
    web.run_app(create_app(cfg), host=cfg.host, port=cfg.port)
