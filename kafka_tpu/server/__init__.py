"""API server tier: aiohttp app, SSE protocol, serving config."""

from .app import build_tpu_provider, create_app, run_server
from .config import ServingConfig

__all__ = ["ServingConfig", "build_tpu_provider", "create_app", "run_server"]
