"""`python -m kafka_tpu.server` — start the serving stack.

Flags mirror ServingConfig; env vars (KAFKA_TPU_*) fill anything not given.
"""

import argparse

from .app import run_server
from .config import ServingConfig


def main() -> None:
    p = argparse.ArgumentParser(prog="kafka_tpu.server")
    p.add_argument("--host", default=None)
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--model", default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--db-path", default=None)
    p.add_argument("--tp-size", type=int, default=None)
    p.add_argument("--sp-size", type=int, default=None,
                   help="sequence-parallel ring width for long-prompt prefill")
    p.add_argument("--pp-size", type=int, default=None,
                   help="pipeline stages for models exceeding one slice's HBM")
    p.add_argument("--dp-size", type=int, default=None,
                   help="data-parallel engine replicas (dp*sp*tp devices)")
    p.add_argument("--ep-size", type=int, default=None,
                   help="expert-parallel width for MoE models (Mixtral)")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--tiny-model", action="store_true",
                   help="serve a tiny random-weight model (dev/demo)")
    args = p.parse_args()

    overrides = {}
    if args.host is not None:
        overrides["host"] = args.host
    if args.port is not None:
        overrides["port"] = args.port
    if args.model is not None:
        overrides["model_name"] = args.model
    if args.checkpoint_dir is not None:
        overrides["checkpoint_dir"] = args.checkpoint_dir
    if args.db_path is not None:
        overrides["db_path"] = args.db_path
    if args.tp_size is not None:
        overrides["tp_size"] = args.tp_size
    if args.sp_size is not None:
        overrides["sp_size"] = args.sp_size
    if args.pp_size is not None:
        overrides["pp_size"] = args.pp_size
    if args.dp_size is not None:
        overrides["dp_size"] = args.dp_size
    if args.ep_size is not None:
        overrides["ep_size"] = args.ep_size
    if args.max_batch is not None:
        overrides["max_batch"] = args.max_batch
    if args.tiny_model:
        overrides["tiny_model"] = True

    run_server(ServingConfig.from_env(**overrides))


if __name__ == "__main__":
    main()
