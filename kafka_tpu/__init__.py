"""kafka_tpu — a TPU-native LLM agent-serving framework.

A from-scratch rebuild of the capability surface of
`egrigokhan/kafka-llm-service` (OpenAI-compatible threaded agent serving)
with the remote LLM gateway replaced by an in-tree JAX/XLA inference engine:
tensor-parallel Llama via jit+shard_map, Pallas TPU kernels, paged KV-cache
keyed by thread_id, and continuous batching across threads.

Layout:
    core/         wire types, sanitization, tool-call accumulation
    models/       Llama model family in functional JAX + HF loaders
    ops/          attention/sampling/rope/norm ops (+ Pallas TPU kernels)
    parallel/     mesh & sharding rules, TP/SP, ring-attention CP
    runtime/      paged KV cache, continuous-batching scheduler, engine
    llm/          LLMProvider ABC, TPUProvider, context compaction
    agents/       tool-calling agent loop
    tools/        tool providers (local / sandbox / MCP)
    prompts/      section-composed system prompts
    sandbox/      sandbox runtime (local HTTP sandboxes, manager, lazy)
    db/           thread persistence (SQLite; Supabase-compatible duck type)
    kafka/        orchestrator wiring it all together
    server/       aiohttp API server + SSE protocol
    server_tools/ built-in tools (weather, counter, shell, notebook, planner)
    utils/        config, logging, metrics
"""

__version__ = "0.1.0"
