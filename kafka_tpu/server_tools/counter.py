"""Streaming demo tool.

Parity: reference server_tools/counter.py:13-44 — `count_slowly` exists to
demonstrate (and test) streamed tool results end to end.
"""

from __future__ import annotations

import asyncio

from ..tools.types import Tool


def counter_tool() -> Tool:
    async def count_slowly(limit: int = 5, delay: float = 0.2):
        for i in range(1, int(limit) + 1):
            yield f"{i}\n"
            await asyncio.sleep(max(0.0, float(delay)))

    return Tool(
        name="count_slowly",
        description=(
            "Counts from 1 to limit, streaming one number at a time. "
            "For demonstrating streaming tool output."
        ),
        parameters={
            "type": "object",
            "properties": {
                "limit": {"type": "integer", "default": 5},
                "delay": {"type": "number", "default": 0.2},
            },
        },
        handler=count_slowly,
    )
