"""Built-in tools (reference server_tools/): planner, counter, weather;
shell/notebook join once a sandbox is configured (sandbox tier)."""

from typing import List, Optional

from ..tools.types import Tool
from .counter import counter_tool
from .planner import PlannerTools, SequentialThinkingServer
from .weather import weather_tool


def builtin_tools(sandbox_url: Optional[str] = None) -> List[Tool]:
    tools: List[Tool] = [
        weather_tool(),
        counter_tool(),
        *PlannerTools().tools(),
    ]
    if sandbox_url:
        # sandbox tools are additive: their failure must not take down the
        # base tool set (mirrors MCP connect-failure handling)
        try:
            from ..sandbox.tools import sandbox_builtin_tools

            tools.extend(sandbox_builtin_tools(sandbox_url))
        except Exception as e:
            import logging

            logging.getLogger("kafka_tpu.server_tools").warning(
                "sandbox tools unavailable (%s); continuing without them", e
            )
    return tools


__all__ = [
    "PlannerTools",
    "SequentialThinkingServer",
    "builtin_tools",
    "counter_tool",
    "weather_tool",
]
