"""Default MCP server configurations.

Parity: reference server_tools/mcp_servers.py:8-13 (a remote `fetch`
server by default). Here the default set is read from the
KAFKA_TPU_MCP_SERVERS env var (JSON list of MCPServerConfig fields) so
deployments choose their own servers; with the var unset we fall back to
the reference's remote fetch server. Connect failures are non-fatal by
design (AgentToolProvider warns and skips), so an offline deployment pays
only a connect timeout — set KAFKA_TPU_MCP_SERVERS='[]' to skip entirely.
"""

from __future__ import annotations

import json
import logging
import os
from typing import List

from ..tools.types import MCPServerConfig

logger = logging.getLogger("kafka_tpu.server_tools")

_REFERENCE_DEFAULT = [
    {"name": "fetch", "url": "https://remote.mcpservers.org/fetch/mcp"},
]


def default_mcp_servers() -> List[MCPServerConfig]:
    raw = os.environ.get("KAFKA_TPU_MCP_SERVERS")
    if raw is None:
        entries = _REFERENCE_DEFAULT
    else:
        try:
            entries = json.loads(raw)
        except json.JSONDecodeError as e:
            logger.warning("KAFKA_TPU_MCP_SERVERS is not valid JSON (%s); "
                           "using no MCP servers", e)
            return []
        if not isinstance(entries, list):
            logger.warning("KAFKA_TPU_MCP_SERVERS must be a JSON list; "
                           "using no MCP servers")
            return []
    configs: List[MCPServerConfig] = []
    for entry in entries:
        try:
            configs.append(MCPServerConfig(**entry))
        except TypeError as e:
            logger.warning("bad MCP server entry %r: %s — skipping",
                           entry, e)
    return configs
