"""Weather lookup tool (Open-Meteo geocode + forecast).

Parity: reference server_tools/weather.py:13-112 — the no-auth live-API
demo tool.  Network failures return an error string (tool errors are data
the model can react to), so the tool is safe to register in offline
environments.
"""

from __future__ import annotations

import json

from ..tools.types import Tool

GEOCODE_URL = "https://geocoding-api.open-meteo.com/v1/search"
FORECAST_URL = "https://api.open-meteo.com/v1/forecast"

WEATHER_CODES = {
    0: "clear sky", 1: "mainly clear", 2: "partly cloudy", 3: "overcast",
    45: "fog", 48: "depositing rime fog", 51: "light drizzle",
    53: "drizzle", 55: "dense drizzle", 61: "light rain", 63: "rain",
    65: "heavy rain", 71: "light snow", 73: "snow", 75: "heavy snow",
    80: "rain showers", 81: "heavy rain showers", 95: "thunderstorm",
}


def weather_tool() -> Tool:
    async def get_weather(location: str) -> str:
        try:
            import httpx

            async with httpx.AsyncClient(timeout=10) as client:
                geo = await client.get(
                    GEOCODE_URL, params={"name": location, "count": 1}
                )
                geo.raise_for_status()
                results = geo.json().get("results") or []
                if not results:
                    return f"No location found for {location!r}."
                place = results[0]
                fc = await client.get(
                    FORECAST_URL,
                    params={
                        "latitude": place["latitude"],
                        "longitude": place["longitude"],
                        "current": "temperature_2m,weather_code,wind_speed_10m",
                    },
                )
                fc.raise_for_status()
                cur = fc.json().get("current", {})
            desc = WEATHER_CODES.get(cur.get("weather_code"), "unknown")
            return json.dumps({
                "location": place.get("name", location),
                "country": place.get("country"),
                "temperature_c": cur.get("temperature_2m"),
                "conditions": desc,
                "wind_kmh": cur.get("wind_speed_10m"),
            })
        except Exception as e:
            return f"Weather lookup failed: {type(e).__name__}: {e}"

    return Tool(
        name="get_weather",
        description="Get current weather for a location by name.",
        parameters={
            "type": "object",
            "properties": {"location": {"type": "string"}},
            "required": ["location"],
        },
        handler=get_weather,
    )
