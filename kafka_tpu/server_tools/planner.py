"""Sequential-thinking planner tools.

Parity: reference server_tools/planner.py:14-307 — a stateful in-process
planning server with numbered thoughts, revisions, branches, and named
checkpoints, exposed as three tools: `sequentialthinking`,
`saveThoughtCheckpoint`, `loadThoughtCheckpoint`.

One reference bug deliberately fixed: its `_thinking_server` was a module
global shared by every thread/request (flagged in SURVEY §5.2).  Here the
server instance is owned by the `PlannerTools` factory — one per wiring —
and thread-keyed internally, so concurrent threads don't interleave plans.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..tools.types import Tool


@dataclass
class Thought:
    number: int
    content: str
    revises: Optional[int] = None
    branch_id: Optional[str] = None


@dataclass
class PlanState:
    thoughts: List[Thought] = field(default_factory=list)
    branches: Dict[str, List[Thought]] = field(default_factory=dict)
    next_number: int = 1


class SequentialThinkingServer:
    """Holds plan state per session key (thread id or 'default')."""

    def __init__(self) -> None:
        self._plans: Dict[str, PlanState] = {}
        self._checkpoints: Dict[str, Dict[str, PlanState]] = {}

    def _plan(self, session: str) -> PlanState:
        return self._plans.setdefault(session, PlanState())

    def think(
        self,
        thought: str,
        session: str = "default",
        thought_number: Optional[int] = None,
        total_thoughts: Optional[int] = None,
        next_thought_needed: bool = True,
        is_revision: bool = False,
        revises_thought: Optional[int] = None,
        branch_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        plan = self._plan(session)
        number = thought_number or plan.next_number
        t = Thought(
            number=number,
            content=thought,
            revises=revises_thought if is_revision else None,
            branch_id=branch_id,
        )
        if branch_id:
            plan.branches.setdefault(branch_id, []).append(t)
        else:
            plan.thoughts.append(t)
        plan.next_number = max(plan.next_number, number) + 1
        return {
            "thought_number": number,
            "total_thoughts": total_thoughts or len(plan.thoughts),
            "next_thought_needed": next_thought_needed,
            "branches": sorted(plan.branches),
            "thought_history_length": len(plan.thoughts),
        }

    def save_checkpoint(self, name: str, session: str = "default") -> Dict[str, Any]:
        plans = self._checkpoints.setdefault(name, {})
        plans[session] = copy.deepcopy(self._plan(session))
        return {"checkpoint": name, "thoughts": len(plans[session].thoughts)}

    def load_checkpoint(self, name: str, session: str = "default") -> Dict[str, Any]:
        plans = self._checkpoints.get(name)
        if plans is None or session not in plans:
            return {"error": f"no checkpoint named {name!r}"}
        self._plans[session] = copy.deepcopy(plans[session])
        state = self._plans[session]
        return {
            "checkpoint": name,
            "thoughts": len(state.thoughts),
            "history": [
                {"number": t.number, "content": t.content}
                for t in state.thoughts
            ],
        }


class PlannerTools:
    """Factory bundling the three planner tools over one server instance."""

    def __init__(self) -> None:
        self.server = SequentialThinkingServer()

    def tools(self) -> List[Tool]:
        srv = self.server

        def sequentialthinking(
            thought: str,
            thoughtNumber: Optional[int] = None,
            totalThoughts: Optional[int] = None,
            nextThoughtNeeded: bool = True,
            isRevision: bool = False,
            revisesThought: Optional[int] = None,
            branchId: Optional[str] = None,
            session: str = "default",
            **_: Any,
        ) -> str:
            return json.dumps(
                srv.think(
                    thought,
                    session=session,
                    thought_number=thoughtNumber,
                    total_thoughts=totalThoughts,
                    next_thought_needed=nextThoughtNeeded,
                    is_revision=isRevision,
                    revises_thought=revisesThought,
                    branch_id=branchId,
                )
            )

        def saveThoughtCheckpoint(name: str, session: str = "default", **_: Any) -> str:
            return json.dumps(srv.save_checkpoint(name, session=session))

        def loadThoughtCheckpoint(name: str, session: str = "default", **_: Any) -> str:
            return json.dumps(srv.load_checkpoint(name, session=session))

        return [
            Tool(
                name="sequentialthinking",
                description=(
                    "Record one step of sequential thinking. Supports "
                    "revising earlier thoughts (isRevision/revisesThought) "
                    "and alternative branches (branchId). Use for planning "
                    "multi-step work before executing it."
                ),
                parameters={
                    "type": "object",
                    "properties": {
                        "thought": {"type": "string"},
                        "thoughtNumber": {"type": "integer"},
                        "totalThoughts": {"type": "integer"},
                        "nextThoughtNeeded": {"type": "boolean"},
                        "isRevision": {"type": "boolean"},
                        "revisesThought": {"type": "integer"},
                        "branchId": {"type": "string"},
                    },
                    "required": ["thought"],
                },
                handler=sequentialthinking,
            ),
            Tool(
                name="saveThoughtCheckpoint",
                description="Save the current plan state under a name.",
                parameters={
                    "type": "object",
                    "properties": {"name": {"type": "string"}},
                    "required": ["name"],
                },
                handler=saveThoughtCheckpoint,
            ),
            Tool(
                name="loadThoughtCheckpoint",
                description="Restore the plan state saved under a name.",
                parameters={
                    "type": "object",
                    "properties": {"name": {"type": "string"}},
                    "required": ["name"],
                },
                handler=loadThoughtCheckpoint,
            ),
        ]
