"""Failpoint fault injection: named sites where tests (or an operator
chasing a production bug) can make the serving stack fail on purpose.

Production LLM servers treat injectable faults as first-class (TiKV's
`fail_point!`, FreeBSD's KFAIL_POINT, gofail): the only way to *prove* the
scheduler frees pages on a mid-decode crash, or that a request whose DB
write dies still gets a terminal event, is to make those crashes happen on
demand.  This module is that seam for kafka_tpu:

* **Sites** are plain strings compiled into the hot paths:
  ``engine.step`` (top of the scheduler iteration), ``engine.prefill``
  (chunk dispatch), ``kv.alloc`` (page allocation), ``worker.dispatch``
  (token-event routing), ``sandbox.exec`` (tool execution, client side),
  ``sandbox.boot`` (subprocess sandbox spawn), ``sandbox.server.exec``
  (tool execution INSIDE the sandbox subprocess), ``dist.init``
  (jax.distributed initialization), ``dist.step`` (a guarded multi-host
  collective), ``db.write`` (thread-store mutation).  The registry is
  open — any string works — but those are the wired ones (see SITES).
* **Rules** attach an action to a site: ``error`` raises
  :class:`FailpointError`, ``delay`` sleeps, ``exit`` hard-kills the
  process (``os._exit``) — the cross-process chaos primitive: armed in a
  sandbox subprocess or a jax.distributed worker it simulates a crashed
  peer, so tests can assert the SURVIVING process degrades cleanly.
  Triggers scope a rule to the ``nth`` call (1-based, fires once) or cap
  total firings with ``count``.
* **Off by default, zero hot-path cost**: every call site goes through
  :func:`failpoint`, whose first line is a module-global bool check — no
  dict lookup, no lock, nothing, until some rule is armed.
* **Cross-process inheritance**: :func:`subprocess_env` serializes the
  currently-armed rules back into the env syntax so child processes
  (sandbox subprocesses, jax.distributed workers) arm the same spec at
  import — chaos reaches across PID boundaries.

Activation is programmatic (``configure`` / the ``armed`` context manager
in tests) or environmental::

    KAFKA_TPU_FAILPOINTS="engine.step=error(boom):nth=3;kv.alloc=delay(0.05)"

Syntax: ``site=action[(arg)][:nth=N][:count=N]``, ``;``-separated.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import os
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger("kafka_tpu.failpoints")

ENV_VAR = "KAFKA_TPU_FAILPOINTS"

# The sites wired into call paths.  This is the DOCUMENTED REGISTRY: a
# static check (tests/test_failpoints.py) asserts every failpoint("<site>")
# call in kafka_tpu/ appears here and vice versa, so new sites cannot ship
# undocumented.  The runtime registry itself stays open (any string works).
SITES = (
    "engine.step",
    "engine.prefill",
    "kv.alloc",
    # Tiered-KV copies (runtime/kv_tier.py): fired once per shipped chunk
    # of a demote (D2H) / promote (H2D), so `error` with nth=2 on a
    # multi-chunk run produces a genuinely TORN copy — a torn demote is
    # discarded before anything is stored, a torn promote frees its
    # destination pages and degrades to re-prefill; `delay` simulates a
    # slow link.
    "kv.demote",
    "kv.promote",
    # Cross-replica page shipping (runtime/kv_tier.CrossReplicaPageShipper,
    # disaggregated prefill/decode): fired once per shipped chunk, so
    # `error` with nth=2 on a multi-chunk run produces a genuinely TORN
    # cross-replica copy — the destination frees every partially-written
    # page and the thread degrades to re-prefill on the decode replica,
    # never partial KV; `delay` simulates a slow inter-replica link.
    "kv.ship",
    # Object-store KV tier (runtime/object_tier.py): fired once per OBJECT
    # (run payload or sleep manifest).  `error` on a put = torn write
    # discarded before the ref/manifest commit (atomic rename; no partial
    # object, no dangling reference — the archive degrades to plain
    # eviction and a sleep entry is skipped); `error` on a get = miss —
    # the whole wake aborts with ALL partially-promoted pages freed and
    # the request degrades to the disk-tier/local hit or a plain
    # re-prefill; `delay` simulates a slow store.
    "kv.object_put",
    "kv.object_get",
    # Store metadata probes (runtime/object_tier.py): `kv.object_head`
    # fires on existence checks — wake truncation's has_run probes and
    # read_manifest's head validation.  `error` = the probe fails closed
    # (absent-shaped): a wake truncates at that run, the router's
    # manifest probe is negatively cached for the breaker's open window;
    # `delay` simulates a slow store stat.  `kv.object_list` fires on
    # listing walks — release's last-ref scan and the fsck scrubber.
    # `error` on release leaves a crash-window orphan (exactly what fsck
    # repairs); `error` on fsck degrades it to a partial report.
    "kv.object_head",
    "kv.object_list",
    # Wake prefetch (runtime/object_tier.WakePrefetcher): fired once per
    # PREFETCHED run, on the prefetch worker thread, before the object
    # GET.  `error` = that run's prefetch is dropped and the wake falls
    # back to today's synchronous fetch (never a failed wake — prefetch
    # is an overlap optimization, not a correctness dependency); `delay`
    # simulates a prefetch racing admission.
    "kv.prefetch",
    # Tool execution (tools/provider.py run_tool_stream): fired once per
    # tool call, before the tool runs.  `delay` injects tool latency —
    # the agent-gap bench arms this to model a slow tool (the gap the
    # agent-native scheduler exploits) without a sandbox round trip;
    # `error` surfaces as a tool-error event, the shape a crashed tool
    # produces, so the agent loop's error turn is reachable in tests.
    "agent.tool",
    "worker.dispatch",
    "sandbox.exec",
    "sandbox.boot",
    "sandbox.server.exec",
    "dist.init",
    "dist.step",
    "db.write",
)

ACTIONS = ("error", "delay", "exit")


class FailpointError(RuntimeError):
    """Raised by an armed ``error`` rule.  Deliberately NOT a subclass of
    any domain error (e.g. OutOfPagesError): an injected fault must take
    the *unhandled*-exception path of the layer it fires in, which is the
    path chaos tests exist to exercise."""

    def __init__(self, site: str, message: str = ""):
        self.site = site
        super().__init__(message or f"injected fault at failpoint {site!r}")


@dataclasses.dataclass
class Rule:
    """One armed rule.  `calls` counts every evaluation at the site;
    `fired` counts actual firings (the difference is trigger filtering)."""

    site: str
    action: str  # "error" | "delay" | "exit"
    arg: str = ""  # error message / delay seconds / exit code (as given)
    nth: Optional[int] = None  # fire ONLY on the nth call (1-based)
    count: Optional[int] = None  # max firings (None = unlimited)
    calls: int = 0
    fired: int = 0

    def _should_fire(self) -> bool:
        self.calls += 1
        if self.nth is not None and self.calls != self.nth:
            return False
        if self.count is not None and self.fired >= self.count:
            return False
        self.fired += 1
        return True

    def _fire(self) -> None:
        if self.action == "delay":
            time.sleep(float(self.arg or 0.01))
            return
        if self.action == "exit":
            # simulate a process crash: no atexit, no finally blocks, no
            # flushed streams — the way a SIGKILL'd peer actually looks to
            # the processes that outlive it
            logger.error("failpoint %s: hard process exit", self.site)
            os._exit(int(self.arg or 1))
        raise FailpointError(self.site, self.arg)


_rules: Dict[str, Rule] = {}
_lock = threading.Lock()
# Module-global fast flag: the ONLY thing disabled call sites touch.
# Reads are GIL-atomic; all writes happen under _lock.
_active = False


def failpoint(site: str) -> None:
    """Hot-path hook.  No-op (one bool check) unless some rule is armed."""
    if not _active:
        return
    with _lock:
        rule = _rules.get(site)
        if rule is None or not rule._should_fire():
            return
    logger.warning("failpoint %s firing: %s(%s)", site, rule.action, rule.arg)
    rule._fire()


def configure(
    site: str,
    action: str,
    arg: str = "",
    nth: Optional[int] = None,
    count: Optional[int] = None,
) -> Rule:
    """Arm one rule (replacing any existing rule at `site`)."""
    if action not in ACTIONS:
        raise ValueError(f"unknown failpoint action {action!r} for {site!r}")
    if any(c in str(arg) for c in ";:)"):
        # the spec metacharacters cannot serialize (format_rules), and an
        # unserializable rule would break subprocess_env — failing every
        # sandbox spawn while an UNRELATED rule is armed.  Fail at arm
        # time instead (parse() can't produce such args syntactically).
        raise ValueError(
            f"failpoint arg {arg!r} for {site!r} may not contain the "
            "spec metacharacters ';' ':' ')'"
        )
    if action == "delay":
        float(arg or 0.01)  # validate now, not at fire time
    elif action == "exit":
        int(arg or 1)
    rule = Rule(site=site, action=action, arg=str(arg), nth=nth, count=count)
    global _active
    with _lock:
        _rules[site] = rule
        _active = True
    return rule


def clear(site: Optional[str] = None) -> None:
    """Disarm one site (or all of them), restoring zero-cost paths."""
    global _active
    with _lock:
        if site is None:
            _rules.clear()
        else:
            _rules.pop(site, None)
        _active = bool(_rules)


def active_rules() -> List[Rule]:
    with _lock:
        return list(_rules.values())


@contextlib.contextmanager
def armed(
    site: str,
    action: str,
    arg: str = "",
    nth: Optional[int] = None,
    count: Optional[int] = None,
):
    """Test scoping: arm a rule for the block, always disarm after."""
    rule = configure(site, action, arg, nth=nth, count=count)
    try:
        yield rule
    finally:
        clear(site)


def parse(spec: str) -> List[Rule]:
    """Parse the env/config syntax into rules (without arming them).

    ``site=action[(arg)][:nth=N][:count=N]`` joined with ``;``.
    """
    rules: List[Rule] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad failpoint spec {part!r}: expected site=action")
        site, rhs = part.split("=", 1)
        pieces = rhs.split(":")
        head, mods = pieces[0].strip(), pieces[1:]
        if "(" in head:
            if not head.endswith(")"):
                raise ValueError(f"bad failpoint action {head!r}")
            action, arg = head[:-1].split("(", 1)
        else:
            action, arg = head, ""
        nth = count = None
        for mod in mods:
            mod = mod.strip()
            if "=" not in mod:
                raise ValueError(f"bad failpoint modifier {mod!r}")
            k, v = mod.split("=", 1)
            if k == "nth":
                nth = int(v)
            elif k == "count":
                count = int(v)
            else:
                raise ValueError(f"unknown failpoint modifier {k!r}")
        if action not in ACTIONS:
            raise ValueError(
                f"unknown failpoint action {action!r} in {part!r}"
            )
        # validate args at parse time, same as configure(): a bad spec
        # must fail on load, not surface as the WRONG failure mode (a
        # recoverable ValueError where the chaos run expected a kill)
        if action == "delay":
            float(arg or 0.01)
        elif action == "exit":
            int(arg or 1)
        rules.append(
            Rule(site=site.strip(), action=action, arg=arg, nth=nth,
                 count=count)
        )
    return rules


def format_rules(rules: List[Rule]) -> str:
    """Serialize rules back into the env syntax (inverse of :func:`parse`).

    Round-trip property (chaos-tested): ``parse(format_rules(parse(s)))``
    produces the same rules as ``parse(s)``.  Args containing the syntax
    metacharacters ``;`` ``:`` ``)`` cannot round-trip and are rejected —
    a spec that silently re-parses differently in the child process would
    make cross-process chaos runs lie.
    """
    parts: List[str] = []
    for r in rules:
        if any(c in r.arg for c in ";:)"):
            raise ValueError(
                f"failpoint arg {r.arg!r} at {r.site!r} cannot be "
                "serialized (contains spec metacharacters)"
            )
        head = f"{r.site}={r.action}"
        if r.arg:
            head += f"({r.arg})"
        if r.nth is not None:
            head += f":nth={r.nth}"
        if r.count is not None:
            head += f":count={r.count}"
        parts.append(head)
    return ";".join(parts)


def subprocess_env(base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for a child process that inherits the armed failpoints.

    Cross-process chaos seam: sandbox subprocesses (sandbox/process.py)
    and jax.distributed workers spawn with this env, so a spec armed in
    the parent — programmatically or via KAFKA_TPU_FAILPOINTS — is live in
    the child from import time (load_env at module bottom).  With nothing
    armed, any stale spec inherited from the parent's own environment is
    scrubbed: a disarmed parent must not spawn pre-armed children.
    """
    env = dict(os.environ if base is None else base)
    spec = format_rules(active_rules())
    if spec:
        env[ENV_VAR] = spec
    else:
        env.pop(ENV_VAR, None)
    return env


def load_env(env: Optional[str] = None) -> int:
    """Arm rules from KAFKA_TPU_FAILPOINTS (idempotent; returns how many).

    Called at import so any process-wide spec is live before the engine
    builds, and again by server startup so late env injection works."""
    spec = env if env is not None else os.environ.get(ENV_VAR, "")
    if not spec:
        return 0
    rules = parse(spec)
    for r in rules:
        configure(r.site, r.action, r.arg, nth=r.nth, count=r.count)
        logger.warning("failpoint armed from env: %s=%s(%s)", r.site,
                       r.action, r.arg)
    return len(rules)


load_env()
