"""ProcessSandboxFactory — sandboxes as local subprocesses, supervised.

The in-tree equivalent of the reference's Daytona cloud factory
(src/sandbox/daytona.py:394-479: create-from-snapshot, connect, restart):
each sandbox is a `python -m kafka_tpu.sandbox.server` subprocess on its
own port, carrying the full sandbox protocol (health/claim/run/reset).
Sandbox ids encode the port (`proc-<port>-<suffix>`) so `connect` can
re-attach after a manager restart without any registry.

Cross-process fault tolerance (ISSUE 2):

* **Liveness-verified hand-back**: `connect`/`restart` check the
  subprocess exit code AND probe the port before returning a Sandbox —
  a crashed subprocess is never handed back as "connected", and its
  zombie handle is reaped from `_procs`.
* **Exit watcher**: every spawn registers a `proc.wait()` task.  An
  unexpected exit (not `terminate`/`restart`-initiated) reaps the
  handle, notifies a crash listener (SandboxManager evicts its ready
  cache so in-flight tool execs get exactly one terminal error from the
  broken HTTP stream, and the next request sees a restart, not a wedge),
  and auto-restarts the sandbox in place with exponential backoff
  (`KAFKA_TPU_SANDBOX_RESTART_BACKOFF_S`, doubling per consecutive
  crash).
* **Crash-loop detector**: more than `KAFKA_TPU_SANDBOX_MAX_RESTARTS`
  unexpected exits inside `crash_window_s` stops the restart loop and
  blacklists the sandbox id — `connect` answers None and the manager
  provisions a fresh sandbox instead of feeding a poisoned one forever.
* **Failpoint inheritance**: subprocesses spawn with
  `failpoints.subprocess_env()`, so specs armed in the parent (including
  the `sandbox.server.exec` site that fires INSIDE the subprocess, and
  the `exit` action that simulates a crash) are live in the child.
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
import sys
import time
import uuid
from typing import Callable, Dict, List, Optional, Set

from .. import failpoints as fp
from .. import tracing
from ..failpoints import failpoint
from .base import Sandbox
from .local import LocalSandbox
from .manager import SandboxFactory
from .types import SandboxError

logger = logging.getLogger("kafka_tpu.sandbox.process")

RESTART_BACKOFF_ENV = "KAFKA_TPU_SANDBOX_RESTART_BACKOFF_S"
MAX_RESTARTS_ENV = "KAFKA_TPU_SANDBOX_MAX_RESTARTS"

# Module-level lifecycle counters, aggregated across factories so
# server/app.py /metrics can report sandbox supervision without a handle
# on every factory instance (factories are created per manager/test).
_counters: Dict[str, int] = {
    "crashes": 0,  # unexpected subprocess exits
    "restarts": 0,  # successful supervised restarts
    "crash_loops": 0,  # ids blacklisted by the crash-loop detector
    "reaped": 0,  # zombie handles removed from _procs
}


def supervisor_snapshot() -> Dict[str, int]:
    return dict(_counters)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ProcessSandboxFactory(SandboxFactory):
    def __init__(
        self,
        boot_timeout_s: float = 30.0,
        restart_backoff_s: Optional[float] = None,
        max_restarts: Optional[int] = None,
        crash_window_s: float = 60.0,
        supervise: bool = True,
    ):
        self.boot_timeout_s = boot_timeout_s
        if restart_backoff_s is None:
            restart_backoff_s = float(
                os.environ.get(RESTART_BACKOFF_ENV, "0.5")
            )
        if max_restarts is None:
            max_restarts = int(os.environ.get(MAX_RESTARTS_ENV, "3"))
        self.restart_backoff_s = restart_backoff_s
        self.max_restarts = max_restarts
        self.crash_window_s = crash_window_s
        self.supervise = supervise
        self._procs: Dict[str, asyncio.subprocess.Process] = {}
        self._watchers: Dict[str, asyncio.Task] = {}
        self._crashes: Dict[str, List[float]] = {}  # recent crash stamps
        self._crash_looping: Set[str] = set()
        # ids being torn down on purpose: their exit is not a crash
        self._terminating: Set[str] = set()
        # SandboxManager registers here (set_crash_listener) to evict its
        # ready cache the moment a subprocess dies
        self._crash_listener: Optional[Callable[[str], None]] = None

    def set_crash_listener(self, fn: Optional[Callable[[str], None]]) -> None:
        self._crash_listener = fn

    @staticmethod
    def _url_for(sandbox_id: str) -> Optional[str]:
        # proc-<port>-<suffix>
        parts = sandbox_id.split("-")
        if len(parts) < 3 or parts[0] != "proc":
            return None
        try:
            port = int(parts[1])
        except ValueError:
            return None
        return f"http://127.0.0.1:{port}"

    # -- spawn + supervision -------------------------------------------

    async def _spawn(self, sandbox_id: str, port: int) -> None:
        failpoint("sandbox.boot")
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "kafka_tpu.sandbox.server",
            "--port", str(port), "--sandbox-id", sandbox_id,
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.DEVNULL,
            # armed failpoint specs AND the tracing/log config propagate:
            # chaos and observability both cross the PID line
            env=tracing.subprocess_env(fp.subprocess_env()),
        )
        self._procs[sandbox_id] = proc
        if self.supervise:
            old = self._watchers.pop(sandbox_id, None)
            # the supervised-restart path reaches here FROM the old
            # watcher task: cancelling ourselves would abort the restart
            if old is not None and old is not asyncio.current_task():
                old.cancel()
            self._watchers[sandbox_id] = asyncio.get_running_loop().create_task(
                self._watch(sandbox_id, proc)
            )

    async def _watch(self, sandbox_id: str,
                     proc: asyncio.subprocess.Process) -> None:
        """Exit watcher: reap, notify, auto-restart with backoff."""
        rc = await proc.wait()
        current = self._procs.get(sandbox_id)
        if (sandbox_id in self._terminating
                or (current is not None and current is not proc)):
            return  # intentional kill, or a newer generation took over
        # current may be None because connect()'s exit-code check reaped
        # the handle before we woke — that is still OUR crash to account
        # (crash-loop detection, listener, restart must not be skipped);
        # intentional paths (terminate/restart) cancel this task first.
        if self._procs.pop(sandbox_id, None) is proc:
            _counters["reaped"] += 1  # not already reaped by connect()
        _counters["crashes"] += 1
        crashed = self._note_crash(sandbox_id)
        logger.error(
            "sandbox %s subprocess died unexpectedly (exit code %s, "
            "crash %d in window)", sandbox_id, rc, crashed,
        )
        if self._crash_listener is not None:
            try:
                self._crash_listener(sandbox_id)
            except Exception:
                logger.exception("sandbox crash listener failed")
        if sandbox_id in self._crash_looping:
            return
        # exponential backoff keyed on the crash density, so a sandbox
        # that dies the moment it boots doesn't spin the CPU respawning
        backoff = self.restart_backoff_s * (2 ** max(0, crashed - 1))
        await asyncio.sleep(backoff)
        if sandbox_id in self._terminating:
            return
        if self._procs.get(sandbox_id) is not None:
            # a newer generation was installed during the backoff (the
            # manager's restart path raced us): killing it to spawn our
            # own would re-break a just-recovered sandbox
            return
        try:
            sandbox = await self.restart(sandbox_id)
        except Exception:
            logger.exception("supervised restart of %s failed", sandbox_id)
            return
        if sandbox is None:
            logger.error("supervised restart of %s failed", sandbox_id)
            return
        _counters["restarts"] += 1
        logger.warning("sandbox %s auto-restarted after crash", sandbox_id)
        await sandbox.aclose()  # the watcher only needed the process back

    def _note_crash(self, sandbox_id: str) -> int:
        """Record one unexpected exit; trip the crash-loop detector when
        the recent-crash count exceeds max_restarts.  Returns the count."""
        now = time.monotonic()
        stamps = self._crashes.setdefault(sandbox_id, [])
        stamps.append(now)
        cutoff = now - self.crash_window_s
        stamps[:] = [t for t in stamps if t >= cutoff]
        if (len(stamps) > self.max_restarts
                and sandbox_id not in self._crash_looping):
            self._crash_looping.add(sandbox_id)
            _counters["crash_loops"] += 1
            logger.error(
                "sandbox %s is crash-looping (%d crashes in %.0fs); "
                "giving up on restarts", sandbox_id, len(stamps),
                self.crash_window_s,
            )
        return len(stamps)

    def _reap_if_dead(
        self, sandbox_id: str
    ) -> Optional[asyncio.subprocess.Process]:
        """Exit-code check: drop a dead handle from _procs; return the
        live process (or None)."""
        proc = self._procs.get(sandbox_id)
        if proc is None:
            return None
        if proc.returncode is not None:
            # without supervision (or before the watcher ran) the handle
            # is a zombie: reap it here so it can't be handed back
            if self._procs.pop(sandbox_id, None) is proc:
                _counters["reaped"] += 1
            return None
        return proc

    async def _wait_live(self, sandbox: LocalSandbox,
                         sandbox_id: str) -> None:
        """Boot probe: poll /health, but fail FAST if the subprocess exits
        — waiting out the full boot timeout against a dead PID would turn
        every boot crash into a 30s stall."""
        deadline = time.monotonic() + self.boot_timeout_s
        while True:
            proc = self._procs.get(sandbox_id)
            if proc is None or proc.returncode is not None:
                rc = proc.returncode if proc is not None else None
                raise SandboxError(
                    f"sandbox {sandbox_id} subprocess exited during boot "
                    f"(exit code {rc})"
                )
            status = await sandbox.check_health()
            if status.get("healthy"):
                return
            if time.monotonic() >= deadline:
                raise SandboxError(
                    f"sandbox {sandbox_id} not live after "
                    f"{self.boot_timeout_s:.0f}s"
                )
            await asyncio.sleep(0.1)

    # -- factory protocol ----------------------------------------------

    async def create(self, thread_id: str) -> Sandbox:
        port = _free_port()
        sandbox_id = f"proc-{port}-{uuid.uuid4().hex[:8]}"
        await self._spawn(sandbox_id, port)
        sandbox = LocalSandbox(self._url_for(sandbox_id), sandbox_id)
        try:
            await self._wait_live(sandbox, sandbox_id)
        except Exception:
            await sandbox.aclose()
            await self.terminate(sandbox_id)
            raise
        logger.info("spawned sandbox %s for thread %s", sandbox_id, thread_id)
        return sandbox

    async def connect(self, sandbox_id: str) -> Optional[Sandbox]:
        url = self._url_for(sandbox_id)
        if url is None:
            return None
        if sandbox_id in self._crash_looping:
            # a poisoned sandbox must not be handed back; the manager
            # falls through to creating a fresh one
            return None
        proc = self._reap_if_dead(sandbox_id)
        sandbox = LocalSandbox(url, sandbox_id)
        # port probe: the only proof a subprocess is actually serving
        status = await sandbox.check_health()
        if status.get("healthy"):
            return sandbox
        if proc is not None:
            # process alive but not serving yet (mid-boot / mid-restart):
            # hand back the handle so the manager can health-poll/restart
            # through us
            return sandbox
        await sandbox.aclose()
        return None

    async def restart(self, sandbox_id: str) -> Optional[Sandbox]:
        url = self._url_for(sandbox_id)
        if url is None:
            return None
        if sandbox_id in self._crash_looping:
            return None
        # retire the old watcher BEFORE killing its process: an old
        # watcher that woke mid-restart would misread the intentional
        # kill as a crash (the supervised-restart path skips this — the
        # current task IS that watcher, past its proc.wait already)
        watcher = self._watchers.pop(sandbox_id, None)
        if watcher is not None and watcher is not asyncio.current_task():
            watcher.cancel()
        old = self._procs.pop(sandbox_id, None)
        if old is not None and old.returncode is None:
            self._terminating.add(sandbox_id)
            try:
                old.kill()
                await old.wait()
            finally:
                self._terminating.discard(sandbox_id)
        elif old is not None:
            _counters["reaped"] += 1
        port = int(sandbox_id.split("-")[1])
        try:
            await self._spawn(sandbox_id, port)
        except Exception as e:
            logger.warning("restart spawn of %s failed: %s", sandbox_id, e)
            return None
        sandbox = LocalSandbox(url, sandbox_id)
        try:
            await self._wait_live(sandbox, sandbox_id)
            return sandbox
        except Exception as e:
            logger.warning("restart of %s failed: %s", sandbox_id, e)
            await sandbox.aclose()
            proc = self._procs.get(sandbox_id)
            if proc is not None and proc.returncode is None:
                # spawned but never went healthy inside the boot budget:
                # orphan hygiene — kill it and retire its watcher.
                # _kill_quiet, NOT terminate(): the crash ledger must
                # survive a failed restart or the loop detector resets.
                await self._kill_quiet(sandbox_id)
            # else: the process DIED rather than stalling — its own
            # watcher is mid-crash-handling (count, backoff, restart);
            # killing that chain here would orphan the supervision
            return None

    async def _kill_quiet(self, sandbox_id: str) -> None:
        """Tear down a sandbox's process/watcher WITHOUT touching the
        crash ledger — failure hygiene, not the operator reset."""
        self._terminating.add(sandbox_id)
        try:
            watcher = self._watchers.pop(sandbox_id, None)
            if (watcher is not None
                    and watcher is not asyncio.current_task()):
                watcher.cancel()
            proc = self._procs.pop(sandbox_id, None)
            if proc is not None and proc.returncode is None:
                proc.kill()
                await proc.wait()
        finally:
            self._terminating.discard(sandbox_id)

    async def terminate(self, sandbox_id: str) -> None:
        await self._kill_quiet(sandbox_id)
        # deliberate teardown also resets supervision history: an
        # operator terminating (or re-provisioning) a sandbox starts it
        # with a clean crash ledger
        self._crashes.pop(sandbox_id, None)
        self._crash_looping.discard(sandbox_id)

    async def aclose(self) -> None:
        # watchers first: one sleeping out a crash backoff would otherwise
        # respawn its sandbox mid-teardown (its id is absent from _procs,
        # so the terminate loop below cannot see the respawn coming)
        for watcher in list(self._watchers.values()):
            watcher.cancel()
        self._watchers.clear()
        for sandbox_id in list(self._procs):
            await self.terminate(sandbox_id)
