"""ProcessSandboxFactory — sandboxes as local subprocesses.

The in-tree equivalent of the reference's Daytona cloud factory
(src/sandbox/daytona.py:394-479: create-from-snapshot, connect, restart):
each sandbox is a `python -m kafka_tpu.sandbox.server` subprocess on its
own port, carrying the full sandbox protocol (health/claim/run/reset).
Sandbox ids encode the port (`proc-<port>-<suffix>`) so `connect` can
re-attach after a manager restart without any registry.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import sys
import uuid
from typing import Dict, Optional

from .base import Sandbox
from .local import LocalSandbox
from .manager import SandboxFactory

logger = logging.getLogger("kafka_tpu.sandbox.process")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ProcessSandboxFactory(SandboxFactory):
    def __init__(self, boot_timeout_s: float = 30.0):
        self.boot_timeout_s = boot_timeout_s
        self._procs: Dict[str, asyncio.subprocess.Process] = {}

    @staticmethod
    def _url_for(sandbox_id: str) -> Optional[str]:
        # proc-<port>-<suffix>
        parts = sandbox_id.split("-")
        if len(parts) < 3 or parts[0] != "proc":
            return None
        try:
            port = int(parts[1])
        except ValueError:
            return None
        return f"http://127.0.0.1:{port}"

    async def _spawn(self, sandbox_id: str, port: int) -> None:
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "kafka_tpu.sandbox.server",
            "--port", str(port), "--sandbox-id", sandbox_id,
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.DEVNULL,
        )
        self._procs[sandbox_id] = proc

    async def create(self, thread_id: str) -> Sandbox:
        port = _free_port()
        sandbox_id = f"proc-{port}-{uuid.uuid4().hex[:8]}"
        await self._spawn(sandbox_id, port)
        sandbox = LocalSandbox(self._url_for(sandbox_id), sandbox_id)
        await sandbox.wait_until_live(
            timeout=self.boot_timeout_s, poll_interval=0.1
        )
        logger.info("spawned sandbox %s for thread %s", sandbox_id, thread_id)
        return sandbox

    async def connect(self, sandbox_id: str) -> Optional[Sandbox]:
        url = self._url_for(sandbox_id)
        if url is None:
            return None
        sandbox = LocalSandbox(url, sandbox_id)
        status = await sandbox.check_health()
        if not status.get("healthy"):
            # process may be gone entirely — only return a handle if the
            # manager might still restart it through us
            if sandbox_id not in self._procs:
                await sandbox.aclose()
                return None
        return sandbox

    async def restart(self, sandbox_id: str) -> Optional[Sandbox]:
        url = self._url_for(sandbox_id)
        if url is None:
            return None
        old = self._procs.pop(sandbox_id, None)
        if old is not None and old.returncode is None:
            old.kill()
            await old.wait()
        port = int(sandbox_id.split("-")[1])
        try:
            await self._spawn(sandbox_id, port)
            sandbox = LocalSandbox(url, sandbox_id)
            await sandbox.wait_until_live(
                timeout=self.boot_timeout_s, poll_interval=0.1
            )
            return sandbox
        except Exception as e:
            logger.warning("restart of %s failed: %s", sandbox_id, e)
            return None

    async def terminate(self, sandbox_id: str) -> None:
        proc = self._procs.pop(sandbox_id, None)
        if proc is not None and proc.returncode is None:
            proc.kill()
            await proc.wait()

    async def aclose(self) -> None:
        for sandbox_id in list(self._procs):
            await self.terminate(sandbox_id)
