"""Remote sandbox provisioning: cloud VMs behind a control-plane API.

The reference provisions tool VMs through the Daytona cloud SDK
(src/sandbox/daytona.py:394-441 create-from-snapshot with fire-and-forget
boot, :443-479 restart, :481-558 connect/stop/delete) and reaches each VM
through a per-sandbox proxy URL (:49-68,
``https://8081-<id>.proxy.daytona.works``).  This is the same capability
expressed as a plain HTTP control plane — no vendor SDK — so any
provisioner that speaks the small REST surface below can back it:

    POST   {api}/sandboxes                {"snapshot", "thread_id"} -> {"id"}
    GET    {api}/sandboxes/{id}           -> {"id", "state"}
    POST   {api}/sandboxes/{id}/restart   -> 200
    DELETE {api}/sandboxes/{id}           -> 200

Each provisioned VM exposes the standard in-VM tool server (sandbox/
server.py protocol: /health, /claim, /run) at a proxy URL derived from a
template, e.g. ``https://8081-{id}.proxy.example.com`` — the returned
handles are ordinary URL-direct sandboxes (sandbox/local.py), exactly the
way the reference's DaytonaSandbox is URL-direct once provisioned.

This factory plugs into SandboxManager wherever a deployment manages
per-thread sandboxes (the library path; see sandbox/manager.py).
`RemoteSandboxFactory.from_env()` builds one from:
    KAFKA_TPU_SANDBOX_API_URL         control-plane base URL
    KAFKA_TPU_SANDBOX_API_KEY         bearer token (optional)
    KAFKA_TPU_SANDBOX_SNAPSHOT        snapshot/image id for new VMs
    KAFKA_TPU_SANDBOX_PROXY_TEMPLATE  e.g. "https://8081-{id}.proxy.x.dev"
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional

import httpx

from .base import Sandbox, SandboxError
from .local import LocalSandbox
from .manager import SandboxFactory

logger = logging.getLogger("kafka_tpu.sandbox.remote")

DEFAULT_BOOT_TIMEOUT_S = 300.0  # reference daytona.py:51-52 (2s poll, 300s)


class RemoteSandboxFactory(SandboxFactory):
    """SandboxFactory over the provisioning REST surface above."""

    def __init__(
        self,
        api_url: str,
        proxy_template: str,
        snapshot: str = "default",
        api_key: str = "",
        boot_timeout_s: float = DEFAULT_BOOT_TIMEOUT_S,
    ):
        self.api_url = api_url.rstrip("/")
        self.proxy_template = proxy_template
        self.snapshot = snapshot
        self.boot_timeout_s = boot_timeout_s
        headers = {}
        if api_key:
            headers["Authorization"] = f"Bearer {api_key}"
        self._client = httpx.AsyncClient(
            base_url=self.api_url, headers=headers, timeout=30.0
        )

    @classmethod
    def from_env(cls) -> Optional["RemoteSandboxFactory"]:
        url = os.environ.get("KAFKA_TPU_SANDBOX_API_URL")
        template = os.environ.get("KAFKA_TPU_SANDBOX_PROXY_TEMPLATE")
        if not url or not template:
            return None
        return cls(
            url,
            template,
            snapshot=os.environ.get("KAFKA_TPU_SANDBOX_SNAPSHOT", "default"),
            api_key=os.environ.get("KAFKA_TPU_SANDBOX_API_KEY", ""),
        )

    def _url_for(self, sandbox_id: str) -> str:
        return self.proxy_template.format(id=sandbox_id)

    # -- SandboxFactory --------------------------------------------------

    async def create(self, thread_id: str) -> Sandbox:
        r = await self._client.post(
            "/sandboxes",
            json={"snapshot": self.snapshot, "thread_id": thread_id},
        )
        r.raise_for_status()
        sandbox_id = r.json()["id"]
        logger.info(
            "provisioned sandbox %s (snapshot %s) for thread %s",
            sandbox_id, self.snapshot, thread_id,
        )
        # fire-and-forget boot (reference daytona.py:431): the VM starts
        # asynchronously; we hand back a handle and wait on its tool server.
        # A VM that never comes up is torn down — it would otherwise keep
        # running (and billing) with nothing referencing it.
        sandbox = LocalSandbox(self._url_for(sandbox_id), sandbox_id)
        try:
            await sandbox.wait_until_live(
                timeout=self.boot_timeout_s, poll_interval=2.0
            )
        except Exception:
            await sandbox.aclose()
            await self.terminate(sandbox_id)
            raise
        return sandbox

    async def connect(self, sandbox_id: str) -> Optional[Sandbox]:
        try:
            r = await self._client.get(f"/sandboxes/{sandbox_id}")
            if r.status_code == 404:
                return None  # genuinely gone: the manager recreates
            r.raise_for_status()
        except httpx.HTTPError as e:
            # transient control-plane failure is NOT "gone" — returning
            # None would make the manager orphan the VM and provision a
            # fresh one, losing the thread's filesystem state; raise a
            # typed error so the attempt fails and retries keep the
            # binding
            raise SandboxError(
                f"control plane error for {sandbox_id}: {e}"
            ) from e
        # the GET is an existence probe: a stopped VM's handle comes back
        # unhealthy and the manager's 3-case lifecycle routes it to
        # restart(); a deleted VM returns None above and gets recreated
        return LocalSandbox(self._url_for(sandbox_id), sandbox_id)

    async def restart(self, sandbox_id: str) -> Optional[Sandbox]:
        try:
            r = await self._client.post(f"/sandboxes/{sandbox_id}/restart")
            if r.status_code == 404:
                return None
            r.raise_for_status()
        except httpx.HTTPError as e:
            logger.warning("restart of %s failed: %s", sandbox_id, e)
            return None
        sandbox = LocalSandbox(self._url_for(sandbox_id), sandbox_id)
        try:
            await sandbox.wait_until_live(
                timeout=self.boot_timeout_s, poll_interval=2.0
            )
        except Exception as e:
            logger.warning("sandbox %s not live after restart: %s",
                           sandbox_id, e)
            await sandbox.aclose()
            return None
        return sandbox

    async def terminate(self, sandbox_id: str) -> None:
        try:
            r = await self._client.delete(f"/sandboxes/{sandbox_id}")
            if r.status_code not in (200, 202, 204, 404):
                r.raise_for_status()
        except httpx.HTTPError as e:
            logger.warning("terminate of %s failed: %s", sandbox_id, e)

    async def aclose(self) -> None:
        await self._client.aclose()
