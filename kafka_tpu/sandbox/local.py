"""LocalSandbox — URL-direct HTTP sandbox client.

Parity: reference src/sandbox/local.py:18-349 — health probe (:125),
`run_tool` as POST /run with the SSE stream parsed from raw BYTES as they
arrive (:207-274; line-buffered readers add latency to streamed tool
output), and /claim (:310).  Also used to talk to subprocess sandboxes
(sandbox/process.py) and any remote implementing the same protocol.
"""

from __future__ import annotations

import json
import logging
from typing import Any, AsyncIterator, Dict, Optional

import httpx

from .. import tracing
from ..failpoints import failpoint
from ..tools.types import ToolEvent
from .base import Sandbox
from .types import SandboxConfig

logger = logging.getLogger("kafka_tpu.sandbox.local")

DEFAULT_TOOL_TIMEOUT_S = 300.0


class LocalSandbox(Sandbox):
    def __init__(
        self,
        url: str,
        sandbox_id: Optional[str] = None,
        client: Optional[httpx.AsyncClient] = None,
    ):
        self.url = url.rstrip("/")
        self.sandbox_id = sandbox_id or self.url
        self._client = client or httpx.AsyncClient(timeout=None)
        self._vm_api_key: Optional[str] = None

    def _auth_headers(self) -> Dict[str, str]:
        if self._vm_api_key:
            return {"Authorization": f"Bearer {self._vm_api_key}"}
        return {}

    async def aclose(self) -> None:
        await self._client.aclose()

    # -- health --------------------------------------------------------

    async def check_health(self) -> Dict[str, Any]:
        try:
            r = await self._client.get(f"{self.url}/health", timeout=5.0)
            r.raise_for_status()
            data = r.json()
            data.setdefault("healthy", True)
            return data
        except Exception as e:
            return {"healthy": False, "claimed": False, "error": str(e)}

    # -- execution -----------------------------------------------------

    async def run_tool(
        self,
        name: str,
        arguments: Dict[str, Any],
        tool_call_id: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> AsyncIterator[ToolEvent]:
        payload = {
            "tool": name,
            "arguments": arguments,
            "tool_call_id": tool_call_id,
            # cross-process trace propagation: the sandbox records its own
            # child spans under this context and ships them back as a
            # {"kind": "spans"} frame, stitched below by trace id
            "trace": tracing.wire_context(),
        }
        timeout = timeout or DEFAULT_TOOL_TIMEOUT_S
        terminal_seen = False
        try:
            # chaos seam: an injected fault takes the transport-error path
            # below, so the agent still receives a terminal tool event
            failpoint("sandbox.exec")
            async with self._client.stream(
                "POST",
                f"{self.url}/run",
                json=payload,
                headers=self._auth_headers(),
                timeout=httpx.Timeout(10.0, read=timeout),
            ) as resp:
                if resp.status_code != 200:
                    body = (await resp.aread()).decode(errors="replace")
                    yield ToolEvent(
                        "error",
                        f"sandbox /run returned {resp.status_code}: {body[:500]}",
                        tool_name=name, tool_call_id=tool_call_id,
                    )
                    return
                # byte-level SSE parse: emit each frame the moment its
                # terminating blank line arrives (reference local.py:207-274)
                buf = b""
                async for chunk in resp.aiter_raw():
                    buf += chunk
                    while b"\n\n" in buf:
                        frame, buf = buf.split(b"\n\n", 1)
                        ev = self._parse_frame(frame, name, tool_call_id)
                        if ev is not None and ev.kind == "spans":
                            # spans recorded inside the sandbox subprocess
                            # (they trail the terminal result): stitch into
                            # the parent trace, never surface to the agent
                            if isinstance(ev.data, dict):
                                tracing.stitch(ev.data)
                            continue
                        if terminal_seen:
                            # post-terminal tail: only the spans frame above
                            # and [DONE] are expected — [DONE] ends the
                            # stream, anything else is dropped (a sandbox
                            # must not stream past its result)
                            if ev is None and b"[DONE]" in frame:
                                return
                            continue
                        if ev is None:
                            continue
                        if ev.terminal:
                            terminal_seen = True
                        yield ev
        except Exception as e:
            # httpx transport errors, malformed URLs (e.g. a sandbox whose
            # port is gone — httpx.InvalidURL subclasses Exception, not
            # HTTPError), and raw socket errors all mean the same thing to
            # the agent: this sandbox is unreachable.  UNLESS the terminal
            # event already went out — then the failure happened during the
            # post-terminal tail (spans frame / [DONE]) and surfacing it
            # would emit a SECOND terminal event for the same call.
            if terminal_seen:
                logger.debug("sandbox stream died after the terminal "
                             "event: %s", e)
                return
            yield ToolEvent(
                "error", f"sandbox connection failed: {e}",
                tool_name=name, tool_call_id=tool_call_id,
            )
            return
        if not terminal_seen:
            # stream ended without a terminal event (sandbox crashed
            # mid-tool): surface that rather than hanging the agent
            yield ToolEvent(
                "error", "sandbox stream ended without a result",
                tool_name=name, tool_call_id=tool_call_id,
            )

    def _parse_frame(
        self, frame: bytes, name: str, tool_call_id: Optional[str]
    ) -> Optional[ToolEvent]:
        for line in frame.split(b"\n"):
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                return None
            try:
                obj = json.loads(payload)
            except json.JSONDecodeError:
                logger.warning("unparseable sandbox SSE frame: %r", payload[:200])
                return None
            return ToolEvent(
                kind=obj.get("kind", "delta"),
                data=obj.get("data"),
                tool_name=name,
                tool_call_id=tool_call_id,
            )
        return None

    # -- lifecycle -----------------------------------------------------

    async def claim(self, config: SandboxConfig) -> bool:
        try:
            r = await self._client.post(
                f"{self.url}/claim", json=config.to_dict(), timeout=10.0
            )
            if r.status_code == 409:
                return False
            r.raise_for_status()
            claimed = bool(r.json().get("claimed"))
            if claimed and config.vm_api_key:
                self._vm_api_key = config.vm_api_key
            return claimed
        except Exception as e:  # unreachable/malformed sandbox == not claimed
            logger.warning("claim failed for %s: %s", self.sandbox_id, e)
            return False

    async def reset(self) -> None:
        try:
            r = await self._client.post(
                f"{self.url}/reset", headers=self._auth_headers(), timeout=10.0
            )
            r.raise_for_status()
            # only a confirmed reset releases the key — the server still
            # requires it otherwise
            self._vm_api_key = None
        except Exception as e:
            logger.warning("reset failed for %s: %s", self.sandbox_id, e)
