"""Sandbox ABC — the tool-execution runtime contract.

Parity: reference src/sandbox/base.py:41-130 — `check_health`,
`wait_until_live`, `run_tool` (streaming), `claim`, `stop`, `reset`,
`terminate`; classmethod-style `create`/`connect` live on factories here
(sandbox/manager.py, sandbox/process.py) because construction policy —
cloud VM vs local subprocess vs warm pool — is deployment configuration,
not sandbox behavior.
"""

from __future__ import annotations

import abc
import asyncio
import time
from typing import Any, AsyncIterator, Dict, Optional

from ..tools.types import ToolEvent
from .types import SandboxConfig, SandboxError, SandboxInfo

HEALTH_POLL_INTERVAL_S = 2.0  # reference daytona.py:51
WAIT_TIMEOUT_S = 300.0  # reference daytona.py:52


class Sandbox(abc.ABC):
    sandbox_id: str

    # -- health --------------------------------------------------------

    @abc.abstractmethod
    async def check_health(self) -> Dict[str, Any]:
        """Quick probe; returns at least {"healthy": bool, "claimed": bool}.
        Never raises — unreachable means {"healthy": False}."""

    async def wait_until_live(
        self,
        timeout: float = WAIT_TIMEOUT_S,
        poll_interval: float = HEALTH_POLL_INTERVAL_S,
    ) -> None:
        """Block until healthy; SandboxError on timeout
        (reference local.py:125-173, daytona.py:134-195)."""
        deadline = time.monotonic() + timeout
        attempt = 0
        while True:
            status = await self.check_health()
            if status.get("healthy"):
                return
            attempt += 1
            if time.monotonic() >= deadline:
                raise SandboxError(
                    f"sandbox {self.sandbox_id} not live after {timeout:.0f}s "
                    f"({attempt} probes)"
                )
            await asyncio.sleep(poll_interval)

    # -- execution -----------------------------------------------------

    @abc.abstractmethod
    def run_tool(
        self,
        name: str,
        arguments: Dict[str, Any],
        tool_call_id: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> AsyncIterator[ToolEvent]:
        """Execute a tool inside the sandbox, streaming events; the last
        event is terminal ("result" or "error")."""

    # -- lifecycle -----------------------------------------------------

    @abc.abstractmethod
    async def claim(self, config: SandboxConfig) -> bool:
        """Bind this sandbox to a thread (injects env/keys). Returns False
        when already claimed by someone else."""

    async def reset(self) -> None:
        """Clear per-thread state, keep the sandbox alive (optional op)."""

    async def stop(self) -> None:
        """Stop the sandbox, keep it restartable (optional op)."""

    async def terminate(self) -> None:
        """Destroy the sandbox permanently (optional op)."""

    async def get_info(self) -> SandboxInfo:
        status = await self.check_health()
        from .types import SandboxState

        return SandboxInfo(
            sandbox_id=self.sandbox_id,
            state=SandboxState.RUNNING
            if status.get("healthy") else SandboxState.UNKNOWN,
            healthy=bool(status.get("healthy")),
            claimed=bool(status.get("claimed")),
        )
