"""Warm sandbox pools — pre-provisioned sandboxes for fast cold starts.

Parity: reference src/warm_sandbox/ — `WarmSandboxFactory` ABC (:base.py:9)
and an HTTP pool client that POSTs `{service}/claim/{env_id}` and swallows
connection errors so an unreachable pool degrades to cold creation
(:daytona.py:30-64).  `ProcessWarmPool` is the in-tree equivalent: it keeps
N subprocess sandboxes booted ahead of demand.
"""

from __future__ import annotations

import abc
import asyncio
import logging
from typing import List, Optional

logger = logging.getLogger("kafka_tpu.sandbox.warm")


class WarmSandboxFactory(abc.ABC):
    @abc.abstractmethod
    async def claim_warm(self) -> Optional[str]:
        """Pop a pre-warmed sandbox id, or None (pool empty/unreachable)."""


class HTTPWarmSandboxFactory(WarmSandboxFactory):
    """Claims from a remote warm-pool service over HTTP."""

    def __init__(self, service_url: str, env_id: str = "default"):
        self.service_url = service_url.rstrip("/")
        self.env_id = env_id

    async def claim_warm(self) -> Optional[str]:
        try:
            import httpx

            async with httpx.AsyncClient(timeout=10.0) as client:
                r = await client.post(
                    f"{self.service_url}/claim/{self.env_id}"
                )
                if r.status_code != 200:
                    return None
                return r.json().get("sandbox_id")
        except Exception as e:  # unreachable pool -> cold create
            logger.warning("warm pool unreachable: %s", e)
            return None


class ProcessWarmPool(WarmSandboxFactory):
    """Keeps `size` subprocess sandboxes pre-booted (refilled lazily)."""

    def __init__(self, factory, size: int = 2):
        # factory: ProcessSandboxFactory (sandbox/process.py)
        self.factory = factory
        self.size = size
        self._pool: List[str] = []
        self._fill_lock = asyncio.Lock()

    async def fill(self) -> None:
        async with self._fill_lock:
            while len(self._pool) < self.size:
                sandbox = await self.factory.create("warm")
                self._pool.append(sandbox.sandbox_id)
                logger.info("warm pool: booted %s (%d/%d)",
                            sandbox.sandbox_id, len(self._pool), self.size)

    async def claim_warm(self) -> Optional[str]:
        if not self._pool:
            return None
        sandbox_id = self._pool.pop(0)
        # refill in the background; failure just means a colder next start
        asyncio.get_running_loop().create_task(self._safe_fill())
        return sandbox_id

    async def _safe_fill(self) -> None:
        try:
            await self.fill()
        except Exception as e:
            logger.warning("warm pool refill failed: %s", e)
