"""The sandbox-side server: what runs INSIDE a sandbox.

The reference's sandboxes are Daytona cloud VMs baked from a snapshot image
whose contents are out-of-repo; the app only speaks their HTTP protocol
(`GET /health`, `POST /claim`, `POST /run` streaming SSE — SURVEY §5.8).
This module implements that protocol in-tree as an aiohttp app, so the
whole sandbox tier runs end-to-end locally: the manager spawns one of these
as a subprocess per thread (sandbox/process.py) the way the reference
provisions a VM per thread.

Tools served:
  * `create_shell` / `shell_exec` — persistent bash sessions (stdout+stderr
    merged, streamed line-by-line; reference server_tools/shell.py)
  * `notebook_run_cell` — persistent Python namespace per kernel with
    stdout capture and last-expression echo (reference notebook.py)
  * `reset` clears shells/kernels; `claim` binds a thread config.

SSE framing: `data: {json ToolEvent}` frames, terminated by `data: [DONE]`
— byte-compatible with what LocalSandbox parses.
"""

from __future__ import annotations

import argparse
import ast
import asyncio
import contextlib
import io
import json
import logging
import uuid
from typing import Any, AsyncIterator, Dict, Optional

from aiohttp import web

logger = logging.getLogger("kafka_tpu.sandbox.server")

SBX_KEY = web.AppKey("sandbox_state", dict)

SHELL_SENTINEL = "__KAFKA_TPU_DONE__"


class ShellSession:
    """One persistent bash process with merged stdout/stderr."""

    def __init__(self, shell_id: str):
        self.shell_id = shell_id
        self.proc: Optional[asyncio.subprocess.Process] = None
        self._lock = asyncio.Lock()

    async def start(self) -> None:
        self.proc = await asyncio.create_subprocess_exec(
            "bash", "--noprofile", "--norc", "-s",
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
        )

    async def exec(
        self, command: str, timeout: float = 30.0
    ) -> AsyncIterator[Dict[str, Any]]:
        """Run one command, yielding output lines then a terminal result."""
        assert self.proc is not None and self.proc.stdin is not None
        async with self._lock:  # one command at a time per shell
            sentinel_cmd = f'\nprintf "%s %s\\n" "{SHELL_SENTINEL}" "$?"\n'
            self.proc.stdin.write((command + sentinel_cmd).encode())
            await self.proc.stdin.drain()
            lines: list = []
            exit_code: Optional[int] = None
            assert self.proc.stdout is not None
            try:
                while True:
                    line = await asyncio.wait_for(
                        self.proc.stdout.readline(), timeout=timeout
                    )
                    if not line:  # shell died
                        yield {"kind": "error",
                               "data": "shell process exited unexpectedly"}
                        return
                    text = line.decode(errors="replace")
                    if text.startswith(SHELL_SENTINEL):
                        try:
                            exit_code = int(text.split()[1])
                        except (IndexError, ValueError):
                            exit_code = -1
                        break
                    lines.append(text)
                    yield {"kind": "delta", "data": text}
            except asyncio.TimeoutError:
                yield {
                    "kind": "error",
                    "data": f"command timed out after {timeout:.0f}s "
                            f"(partial output: {''.join(lines)[-2000:]!r})",
                }
                # the shell may still be running the command; kill and
                # replace the process so the session stays usable
                self.proc.kill()
                await self.start()
                return
            output = "".join(lines)
            result = output if exit_code == 0 else (
                f"{output}\n[exit code: {exit_code}]"
            )
            yield {"kind": "result", "data": result}

    def close(self) -> None:
        if self.proc is not None and self.proc.returncode is None:
            self.proc.kill()


class NotebookKernel:
    """Persistent exec namespace with stdout capture + last-expr echo."""

    def __init__(self, kernel_id: str):
        self.kernel_id = kernel_id
        self.ns: Dict[str, Any] = {"__name__": "__main__"}

    def run_cell(self, code: str) -> str:
        buf = io.StringIO()
        try:
            tree = ast.parse(code, mode="exec")
        except SyntaxError as e:
            raise RuntimeError(f"SyntaxError: {e}") from e
        last_expr: Optional[ast.Expression] = None
        if tree.body and isinstance(tree.body[-1], ast.Expr):
            last_expr = ast.Expression(tree.body.pop().value)
        with contextlib.redirect_stdout(buf):
            exec(compile(tree, "<cell>", "exec"), self.ns)  # noqa: S102
            if last_expr is not None:
                value = eval(compile(last_expr, "<cell>", "eval"), self.ns)  # noqa: S307
                if value is not None:
                    print(repr(value), file=buf)
        return buf.getvalue()


def create_sandbox_app(sandbox_id: Optional[str] = None) -> web.Application:
    app = web.Application()
    app[SBX_KEY] = {
        "sandbox_id": sandbox_id or f"sbx-{uuid.uuid4().hex[:12]}",
        "claimed": False,
        "claim_config": None,
        "shells": {},  # shell_id -> ShellSession
        "kernels": {},  # kernel_id -> NotebookKernel
    }
    r = app.router
    r.add_get("/health", health)
    r.add_post("/claim", claim)
    r.add_post("/run", run_tool)
    r.add_post("/reset", reset)
    app.on_cleanup.append(_cleanup)
    return app


async def _cleanup(app: web.Application) -> None:
    for shell in app[SBX_KEY]["shells"].values():
        shell.close()


async def health(request: web.Request) -> web.Response:
    s = request.app[SBX_KEY]
    return web.json_response({
        "healthy": True,
        "claimed": s["claimed"],
        "sandbox_id": s["sandbox_id"],
        "shells": sorted(s["shells"]),
        "kernels": sorted(s["kernels"]),
    })


async def claim(request: web.Request) -> web.Response:
    s = request.app[SBX_KEY]
    try:
        config = await request.json()
    except Exception:
        config = {}
    if s["claimed"] and s["claim_config"] and config.get("thread_id") not in (
        None, (s["claim_config"] or {}).get("thread_id")
    ):
        return web.json_response(
            {"claimed": False, "error": "already claimed by another thread"},
            status=409,
        )
    s["claimed"] = True
    s["claim_config"] = config
    return web.json_response({"claimed": True, "sandbox_id": s["sandbox_id"]})


async def reset(request: web.Request) -> web.Response:
    s = request.app[SBX_KEY]
    for shell in s["shells"].values():
        shell.close()
    s["shells"].clear()
    s["kernels"].clear()
    s["claimed"] = False
    s["claim_config"] = None
    return web.json_response({"reset": True})


async def run_tool(request: web.Request) -> web.StreamResponse:
    s = request.app[SBX_KEY]
    body = await request.json()
    name = body.get("tool") or body.get("name")
    args = body.get("arguments") or {}
    if isinstance(args, str):
        try:
            args = json.loads(args)
        except json.JSONDecodeError:
            args = {"_raw": args}

    resp = web.StreamResponse(
        status=200,
        headers={"Content-Type": "text/event-stream",
                 "Cache-Control": "no-cache"},
    )
    await resp.prepare(request)

    async def send(event: Dict[str, Any]) -> None:
        await resp.write(
            b"data: " + json.dumps(event, separators=(",", ":")).encode()
            + b"\n\n"
        )

    try:
        if name == "create_shell":
            shell_id = args.get("shell_id") or f"shell-{len(s['shells'])}"
            if shell_id not in s["shells"]:
                session = ShellSession(shell_id)
                await session.start()
                s["shells"][shell_id] = session
            await send({"kind": "result",
                        "data": json.dumps({"shell_id": shell_id})})
        elif name == "shell_exec":
            shell_id = args.get("shell_id") or "default"
            if shell_id not in s["shells"]:
                session = ShellSession(shell_id)
                await session.start()
                s["shells"][shell_id] = session
            timeout = float(args.get("timeout", 30.0))
            async for ev in s["shells"][shell_id].exec(
                args.get("command", ""), timeout=timeout
            ):
                await send(ev)
        elif name == "notebook_run_cell":
            kernel_id = args.get("kernel_id") or "default"
            kernel = s["kernels"].setdefault(
                kernel_id, NotebookKernel(kernel_id)
            )
            timeout = float(args.get("timeout", 300.0))
            try:
                out = await asyncio.wait_for(
                    asyncio.to_thread(kernel.run_cell, args.get("code", "")),
                    timeout=timeout,
                )
                await send({"kind": "result", "data": out})
            except asyncio.TimeoutError:
                await send({"kind": "error",
                            "data": f"cell timed out after {timeout:.0f}s"})
            except Exception as e:
                await send({"kind": "error",
                            "data": f"{type(e).__name__}: {e}"})
        else:
            await send({"kind": "error", "data": f"unknown sandbox tool: {name}"})
    except Exception as e:
        logger.exception("sandbox tool failed")
        with contextlib.suppress(Exception):
            await send({"kind": "error", "data": f"{type(e).__name__}: {e}"})
    with contextlib.suppress(Exception):
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
    return resp


def main() -> None:
    p = argparse.ArgumentParser(prog="kafka_tpu.sandbox.server")
    p.add_argument("--port", type=int, default=8081)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--sandbox-id", default=None)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    web.run_app(
        create_sandbox_app(args.sandbox_id), host=args.host, port=args.port
    )


if __name__ == "__main__":
    main()
