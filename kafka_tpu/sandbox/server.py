"""The sandbox-side server: what runs INSIDE a sandbox.

The reference's sandboxes are Daytona cloud VMs baked from a snapshot image
whose contents are out-of-repo; the app only speaks their HTTP protocol
(`GET /health`, `POST /claim`, `POST /run` streaming SSE — SURVEY §5.8).
This module implements that protocol in-tree as an aiohttp app, so the
whole sandbox tier runs end-to-end locally: the manager spawns one of these
as a subprocess per thread (sandbox/process.py) the way the reference
provisions a VM per thread.

Tools served:
  * `create_shell` / `shell_exec` — persistent bash sessions (stdout+stderr
    merged, streamed line-by-line; reference server_tools/shell.py)
  * `notebook_run_cell` — persistent Python namespace per kernel with
    stdout capture and last-expression echo (reference notebook.py)
  * `reset` clears shells/kernels; `claim` binds a thread config.

SSE framing: `data: {json ToolEvent}` frames, terminated by `data: [DONE]`
— byte-compatible with what LocalSandbox parses.
"""

from __future__ import annotations

import argparse
import ast
import asyncio
import contextlib
import io
import json
import logging
import os
import uuid
from typing import Any, AsyncIterator, Dict, Optional

from aiohttp import web

# Importing the failpoint module arms any KAFKA_TPU_FAILPOINTS spec from
# the environment (load_env at module bottom) — this is how a spec armed
# in the parent reaches the sandbox subprocess (process.py spawns with
# failpoints.subprocess_env()).  kafka_tpu.failpoints is import-light by
# design: no JAX, nothing heavy enters the sandbox process.  The tracing
# module is import-light for the same reason: /run payloads carry the
# parent's trace context, the spans recorded HERE (the child side of the
# PID boundary) ship back as a trailing {"kind": "spans"} SSE frame.
from .. import tracing
from ..failpoints import failpoint

logger = logging.getLogger("kafka_tpu.sandbox.server")

SBX_KEY = web.AppKey("sandbox_state", dict)


class ShellSession:
    """One persistent bash process with merged stdout/stderr."""

    def __init__(self, shell_id: str):
        self.shell_id = shell_id
        self.proc: Optional[asyncio.subprocess.Process] = None
        self._lock = asyncio.Lock()
        self._needs_respawn = False

    async def start(self) -> None:
        if self.proc is not None and self.proc.returncode is None:
            self.proc.kill()
        self.proc = await asyncio.create_subprocess_exec(
            "bash", "--noprofile", "--norc", "-s",
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT,
        )

    async def exec(
        self, command: str, timeout: float = 30.0
    ) -> AsyncIterator[Dict[str, Any]]:
        """Run one command, yielding output lines then a terminal result.

        Commands run directly in the persistent shell (not a subshell) so
        state like `cd`/exports persists across calls. Recovery invariants:

        * a shell-terminating command (`exit 3`, a crash) ends the process
          before the sentinel prints — the shell's own exit status becomes
          the command's exit code and the next exec() respawns the shell;
        * a command that leaves the shell in an unknown state (timeout, or
          the HTTP client disconnecting mid-stream, which cancels this
          generator at a yield) is killed in the `finally` below — no
          `await` there, so it runs even under CancelledError/GeneratorExit
          — and the next exec() respawns.
        """
        async with self._lock:  # one command at a time per shell
            if (self._needs_respawn or self.proc is None
                    or self.proc.returncode is not None):
                await self.start()
                self._needs_respawn = False
            assert self.proc.stdin is not None and self.proc.stdout is not None
            # Per-exec random sentinel: output lines can never spoof it.
            # The printf SPLITS the sentinel across two arguments so the
            # contiguous sentinel string never appears in the command text
            # itself — a stdin-consuming command (`cat`) that swallows and
            # echoes the printf line as data therefore cannot false-match;
            # only the expanded printf output contains the joined sentinel.
            token = uuid.uuid4().hex
            sentinel = f"__KAFKA_TPU_DONE_{token}__"
            sentinel_cmd = (
                f'\nprintf "%s%s %s\\n" "__KAFKA_TPU_DONE_" "{token}__" "$?"\n'
            )
            # True while the shell may still be mid-command; cleared just
            # before the terminal yield so a consumer that stops at the
            # terminal event doesn't get its healthy shell killed.
            dirty = True
            try:
                try:
                    self.proc.stdin.write((command + sentinel_cmd).encode())
                    await self.proc.stdin.drain()
                except (BrokenPipeError, ConnectionResetError):
                    # the pipe may break before the child is reaped
                    # (returncode still None), so flag the respawn
                    # explicitly rather than relying on returncode
                    self._needs_respawn = True
                    dirty = False
                    yield {"kind": "error",
                           "data": "shell was dead; respawning — retry"}
                    return
                lines: list = []
                exit_code: Optional[int] = None
                try:
                    while True:
                        line = await asyncio.wait_for(
                            self.proc.stdout.readline(), timeout=timeout
                        )
                        if not line:  # stdout EOF: shell exited (`exit N`)…
                            try:
                                exit_code = await asyncio.wait_for(
                                    self.proc.wait(), timeout=5.0
                                )
                            except asyncio.TimeoutError:
                                # …or bash closed its own stdout but lives
                                # on (e.g. `exec >&-`) — unusable either
                                # way; kill rather than hold the lock
                                self.proc.kill()
                                exit_code = await self.proc.wait()
                            break
                        text = line.decode(errors="replace")
                        # match mid-line too: output without a trailing
                        # newline shares a line with the sentinel printf
                        idx = text.find(sentinel)
                        if idx != -1:
                            if idx > 0:
                                lines.append(text[:idx])
                                yield {"kind": "delta", "data": text[:idx]}
                            try:
                                exit_code = int(text[idx:].split()[1])
                            except (IndexError, ValueError):
                                exit_code = -1
                            break
                        lines.append(text)
                        yield {"kind": "delta", "data": text}
                except asyncio.TimeoutError:
                    # dirty stays True: the shell may still be running the
                    # command; the finally kills it, next exec respawns
                    yield {
                        "kind": "error",
                        "data": f"command timed out after {timeout:.0f}s "
                                f"(partial output: {''.join(lines)[-2000:]!r})",
                    }
                    return
                output = "".join(lines)
                result = output if exit_code == 0 else (
                    f"{output}\n[exit code: {exit_code}]"
                )
                dirty = False
                yield {"kind": "result", "data": result}
            finally:
                if dirty:
                    self._needs_respawn = True
                    if self.proc is not None and self.proc.returncode is None:
                        self.proc.kill()

    def close(self) -> None:
        if self.proc is not None and self.proc.returncode is None:
            self.proc.kill()


class NotebookKernel:
    """Persistent exec namespace with stdout capture + last-expr echo."""

    def __init__(self, kernel_id: str):
        self.kernel_id = kernel_id
        self.ns: Dict[str, Any] = {"__name__": "__main__"}

    def run_cell(self, code: str) -> str:
        buf = io.StringIO()
        try:
            tree = ast.parse(code, mode="exec")
        except SyntaxError as e:
            raise RuntimeError(f"SyntaxError: {e}") from e
        last_expr: Optional[ast.Expression] = None
        if tree.body and isinstance(tree.body[-1], ast.Expr):
            last_expr = ast.Expression(tree.body.pop().value)
        with contextlib.redirect_stdout(buf):
            exec(compile(tree, "<cell>", "exec"), self.ns)  # noqa: S102
            if last_expr is not None:
                value = eval(compile(last_expr, "<cell>", "eval"), self.ns)  # noqa: S307
                if value is not None:
                    print(repr(value), file=buf)
        return buf.getvalue()


def create_sandbox_app(sandbox_id: Optional[str] = None) -> web.Application:
    app = web.Application()
    app[SBX_KEY] = {
        "sandbox_id": sandbox_id or f"sbx-{uuid.uuid4().hex[:12]}",
        "claimed": False,
        "claim_config": None,
        "shells": {},  # shell_id -> ShellSession
        "kernels": {},  # kernel_id -> NotebookKernel
    }
    r = app.router
    r.add_get("/health", health)
    r.add_post("/claim", claim)
    r.add_post("/run", run_tool)
    r.add_post("/reset", reset)
    app.on_cleanup.append(_cleanup)
    return app


async def _cleanup(app: web.Application) -> None:
    for shell in app[SBX_KEY]["shells"].values():
        shell.close()


async def health(request: web.Request) -> web.Response:
    s = request.app[SBX_KEY]
    return web.json_response({
        "healthy": True,
        "claimed": s["claimed"],
        "sandbox_id": s["sandbox_id"],
        "shells": sorted(s["shells"]),
        "kernels": sorted(s["kernels"]),
    })


async def claim(request: web.Request) -> web.Response:
    s = request.app[SBX_KEY]
    try:
        config = await request.json()
    except Exception:
        # a malformed body must not become a real (keyless, threadless)
        # claim that then 409-blocks the legitimate owner
        return web.json_response(
            {"claimed": False, "error": "claim body must be a JSON object"},
            status=400,
        )
    if not isinstance(config, dict):
        return web.json_response(
            {"claimed": False, "error": "claim body must be a JSON object"},
            status=400,
        )
    existing = s["claim_config"] or {}
    existing_key = existing.get("vm_api_key")
    if s["claimed"]:
        if existing_key:
            # Once claimed with a key, re-claiming (which would overwrite
            # the claim config, including the key) itself requires the key
            # — otherwise an unauthenticated empty claim wipes the auth
            # contract. A key holder may refresh without a thread_id.
            presented = config.get("vm_api_key")
            header = request.headers.get("Authorization")
            if presented != existing_key and header != f"Bearer {existing_key}":
                return web.json_response(
                    {"claimed": False,
                     "error": "missing or invalid vm_api_key"},
                    status=401,
                )
            if config.get("thread_id") not in (None, existing.get("thread_id")):
                return web.json_response(
                    {"claimed": False,
                     "error": "already claimed by another thread"},
                    status=409,
                )
        # Keyless claim: only the exact same thread (or anyone, when no
        # thread owns it) may overwrite the claim config — a claim
        # presenting a NEW key must not be able to take over and lock the
        # keyless owner out.
        elif (existing.get("thread_id") is not None
              and config.get("thread_id") != existing.get("thread_id")):
            return web.json_response(
                {"claimed": False,
                 "error": "already claimed by another thread"},
                status=409,
            )
    # Merge rather than replace: a key-holder refresh that authenticated
    # via the Authorization header (body without vm_api_key) must not wipe
    # the stored key — that would disable /run//reset auth; same for an
    # omitted thread_id erasing the thread binding.
    merged = dict(config)
    for sticky in ("vm_api_key", "thread_id"):
        if merged.get(sticky) is None and existing.get(sticky) is not None:
            merged[sticky] = existing[sticky]
    s["claimed"] = True
    s["claim_config"] = merged
    return web.json_response({"claimed": True, "sandbox_id": s["sandbox_id"]})


def _auth_error(request: web.Request) -> Optional[web.Response]:
    """Enforce the claim-config contract: once a claim carries a
    vm_api_key, /run and /reset require it as a Bearer token."""
    s = request.app[SBX_KEY]
    key = (s["claim_config"] or {}).get("vm_api_key")
    if not key:
        return None
    if request.headers.get("Authorization") == f"Bearer {key}":
        return None
    return web.json_response(
        {"error": "missing or invalid vm_api_key"}, status=401
    )


async def reset(request: web.Request) -> web.Response:
    err = _auth_error(request)
    if err is not None:
        return err
    s = request.app[SBX_KEY]
    for shell in s["shells"].values():
        shell.close()
    s["shells"].clear()
    s["kernels"].clear()
    s["claimed"] = False
    s["claim_config"] = None
    return web.json_response({"reset": True})


async def run_tool(request: web.Request) -> web.StreamResponse:
    err = _auth_error(request)
    if err is not None:
        return err
    s = request.app[SBX_KEY]
    body = await request.json()
    name = body.get("tool") or body.get("name")
    args = body.get("arguments") or {}
    if isinstance(args, str):
        try:
            args = json.loads(args)
        except json.JSONDecodeError:
            args = {"_raw": args}

    resp = web.StreamResponse(
        status=200,
        headers={"Content-Type": "text/event-stream",
                 "Cache-Control": "no-cache"},
    )
    await resp.prepare(request)

    async def send(event: Dict[str, Any]) -> None:
        await resp.write(
            b"data: " + json.dumps(event, separators=(",", ":")).encode()
            + b"\n\n"
        )

    # child-side span collection: present iff the parent traced this
    # request (the /run payload carries its context).  Spans recorded here
    # live in THIS process; they ship back after the terminal event.
    collector = tracing.child_collector(body.get("trace"))
    span_cm = (
        collector.span(
            "sandbox.exec",
            attrs={"tool": name, "pid": os.getpid(),
                   "sandbox_id": s["sandbox_id"]},
        )
        if collector is not None else contextlib.nullcontext()
    )
    try:
        # chaos seam INSIDE the sandbox process: `error` degrades to a
        # terminal error event on the stream; `exit` simulates the whole
        # subprocess crashing mid-tool (the client sees the stream die and
        # must surface exactly one terminal error — sandbox/local.py)
        with span_cm:
            await _run_named_tool(s, name, args, send)
    except Exception as e:
        logger.exception("sandbox tool failed")
        with contextlib.suppress(Exception):
            await send({"kind": "error", "data": f"{type(e).__name__}: {e}"})
    if collector is not None and collector.spans:
        # trailing frame, before [DONE]: the parent's LocalSandbox stitches
        # these into its trace by trace id and drops them from tool output
        with contextlib.suppress(Exception):
            await send({"kind": "spans", "data": collector.export()})
    with contextlib.suppress(Exception):
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
    return resp


async def _run_named_tool(s, name, args, send) -> None:
    """Dispatch one named sandbox tool, streaming events through `send`."""
    failpoint("sandbox.server.exec")
    if name == "create_shell":
        shell_id = args.get("shell_id") or f"shell-{len(s['shells'])}"
        if shell_id not in s["shells"]:
            session = ShellSession(shell_id)
            await session.start()
            s["shells"][shell_id] = session
        await send({"kind": "result",
                    "data": json.dumps({"shell_id": shell_id})})
    elif name == "shell_exec":
        shell_id = args.get("shell_id") or "default"
        if shell_id not in s["shells"]:
            session = ShellSession(shell_id)
            await session.start()
            s["shells"][shell_id] = session
        timeout = float(args.get("timeout", 30.0))
        async for ev in s["shells"][shell_id].exec(
            args.get("command", ""), timeout=timeout
        ):
            await send(ev)
    elif name == "notebook_run_cell":
        kernel_id = args.get("kernel_id") or "default"
        kernel = s["kernels"].setdefault(
            kernel_id, NotebookKernel(kernel_id)
        )
        timeout = float(args.get("timeout", 300.0))
        try:
            out = await asyncio.wait_for(
                asyncio.to_thread(kernel.run_cell, args.get("code", "")),
                timeout=timeout,
            )
            await send({"kind": "result", "data": out})
        except asyncio.TimeoutError:
            await send({"kind": "error",
                        "data": f"cell timed out after {timeout:.0f}s"})
        except Exception as e:
            await send({"kind": "error",
                        "data": f"{type(e).__name__}: {e}"})
    else:
        await send({"kind": "error", "data": f"unknown sandbox tool: {name}"})


def main() -> None:
    p = argparse.ArgumentParser(prog="kafka_tpu.sandbox.server")
    p.add_argument("--port", type=int, default=8081)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--sandbox-id", default=None)
    args = p.parse_args()
    # KAFKA_TPU_LOG_FORMAT=json inherited from the parent process
    # (tracing.subprocess_env): sandbox log lines carry the same
    # trace_id/thread_id correlation keys as the server's
    from ..logs import setup_logging

    setup_logging()
    web.run_app(
        create_sandbox_app(args.sandbox_id), host=args.host, port=args.port
    )


if __name__ == "__main__":
    main()
