"""LazySandbox — deferred-resolution proxy.

Parity: reference src/sandbox/lazy.py:19-124.  The LLM starts streaming
immediately while the real sandbox boots in the background; the FIRST tool
call blocks in `_ensure_resolved`, polling the manager's ready cache every
200ms under an asyncio lock (double-checked) with a 120s timeout.
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING, Any, AsyncIterator, Dict, Optional

from ..tools.types import ToolEvent
from .base import Sandbox
from .types import SandboxConfig, SandboxError

if TYPE_CHECKING:
    from .manager import SandboxManager

logger = logging.getLogger("kafka_tpu.sandbox.lazy")

RESOLVE_POLL_INTERVAL_S = 0.2  # reference lazy.py:124
RESOLVE_TIMEOUT_S = 120.0  # reference server.py:228


class LazySandbox(Sandbox):
    def __init__(
        self,
        thread_id: str,
        manager: "SandboxManager",
        timeout: float = RESOLVE_TIMEOUT_S,
    ):
        self.thread_id = thread_id
        self.sandbox_id = f"lazy:{thread_id}"
        self.manager = manager
        self.timeout = timeout
        self._resolved: Optional[Sandbox] = None
        self._resolve_lock = asyncio.Lock()

    async def _ensure_resolved(self) -> Sandbox:
        if self._resolved is not None:
            return self._resolved
        async with self._resolve_lock:
            if self._resolved is not None:  # double-check under the lock
                return self._resolved
            deadline = (
                asyncio.get_running_loop().time() + self.timeout
            )
            while True:
                sandbox = await self.manager.get_sandbox_if_ready(self.thread_id)
                if sandbox is not None:
                    self._resolved = sandbox
                    self.sandbox_id = sandbox.sandbox_id
                    return sandbox
                if asyncio.get_running_loop().time() >= deadline:
                    raise SandboxError(
                        f"sandbox for thread {self.thread_id} not ready "
                        f"after {self.timeout:.0f}s"
                    )
                await asyncio.sleep(RESOLVE_POLL_INTERVAL_S)

    # -- Sandbox interface: everything defers --------------------------

    async def check_health(self) -> Dict[str, Any]:
        if self._resolved is None:
            return {"healthy": False, "claimed": False, "resolving": True}
        return await self._resolved.check_health()

    async def run_tool(
        self,
        name: str,
        arguments: Dict[str, Any],
        tool_call_id: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> AsyncIterator[ToolEvent]:
        try:
            sandbox = await self._ensure_resolved()
        except SandboxError as e:
            yield ToolEvent("error", str(e), tool_name=name,
                            tool_call_id=tool_call_id)
            return
        async for ev in sandbox.run_tool(
            name, arguments, tool_call_id=tool_call_id, timeout=timeout
        ):
            yield ev

    async def claim(self, config: SandboxConfig) -> bool:
        sandbox = await self._ensure_resolved()
        return await sandbox.claim(config)

    async def reset(self) -> None:
        if self._resolved is not None:
            await self._resolved.reset()
