"""Sandbox tier types.

Parity: reference src/sandbox/types.py (SandboxConfig :10, SandboxInfo :38)
and src/sandbox/base.py:15-27 (SandboxState, SandboxError).  The streaming
`ToolEvent` lives in tools/types.py (shared with local tools).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class SandboxState(str, enum.Enum):
    CREATING = "creating"
    RUNNING = "running"
    STOPPED = "stopped"
    ERROR = "error"
    UNKNOWN = "unknown"


class SandboxError(Exception):
    pass


@dataclass
class SandboxConfig:
    """Claim-time configuration injected into a sandbox.

    Parity: the claim-config env the reference builds per thread
    (src/sandbox/manager.py:85-147): thread id, API key, model access,
    memory DSN, arbitrary env.
    """

    thread_id: Optional[str] = None
    vm_api_key: Optional[str] = None
    env: Dict[str, str] = field(default_factory=dict)
    tool_timeout_s: float = 300.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "thread_id": self.thread_id,
            "vm_api_key": self.vm_api_key,
            "env": self.env,
            "tool_timeout_s": self.tool_timeout_s,
        }


@dataclass
class SandboxInfo:
    sandbox_id: str
    state: SandboxState = SandboxState.UNKNOWN
    url: Optional[str] = None
    healthy: bool = False
    claimed: bool = False
    metadata: Dict[str, Any] = field(default_factory=dict)
