"""SandboxTool — tools that execute inside a sandbox.

Parity: reference src/tools/types.py:222-374 (`SandboxTool` forwards to
`Sandbox.run_tool` after a health wait) and server_tools/shell.py:35-75 /
notebook.py:39-72 (the shell/notebook tool definitions with their 30s/300s
health timeouts).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional

from ..tools.types import Tool, ToolEvent
from .base import Sandbox
from .types import SandboxError

SHELL_HEALTH_TIMEOUT_S = 30.0  # reference server.py:121
NOTEBOOK_HEALTH_TIMEOUT_S = 300.0  # reference server.py:122


@dataclass
class SandboxTool(Tool):
    """A Tool executed by a sandbox rather than an in-process handler."""

    sandbox: Optional[Sandbox] = None
    health_timeout_s: float = SHELL_HEALTH_TIMEOUT_S
    #: sandbox-side tool name (defaults to this tool's name)
    remote_name: Optional[str] = None
    source: str = "sandbox"
    default_arguments: Dict[str, Any] = field(default_factory=dict)

    def bind(self, sandbox: Sandbox) -> "SandboxTool":
        self.sandbox = sandbox
        return self

    async def run_stream(
        self, arguments: Dict[str, Any]
    ) -> AsyncIterator[ToolEvent]:
        if self.sandbox is None:
            yield ToolEvent(
                "error",
                f"tool {self.name} has no sandbox bound for this thread",
                tool_name=self.name,
            )
            return
        try:
            await self.sandbox.wait_until_live(
                timeout=self.health_timeout_s, poll_interval=0.5
            )
        except SandboxError as e:
            yield ToolEvent("error", str(e), tool_name=self.name)
            return
        merged = {**self.default_arguments, **arguments}
        async for ev in self.sandbox.run_tool(
            self.remote_name or self.name, merged
        ):
            ev.tool_name = self.name
            yield ev


def shell_tools(sandbox: Optional[Sandbox] = None) -> List[SandboxTool]:
    """`create_shell` / `shell_exec` (reference server_tools/shell.py)."""
    return [
        SandboxTool(
            name="create_shell",
            description=(
                "Create (or reuse) a named persistent shell session in the "
                "sandbox. Returns the shell_id to pass to shell_exec."
            ),
            parameters={
                "type": "object",
                "properties": {"shell_id": {"type": "string"}},
            },
            sandbox=sandbox,
            health_timeout_s=SHELL_HEALTH_TIMEOUT_S,
        ),
        SandboxTool(
            name="shell_exec",
            description=(
                "Run a shell command in a persistent sandbox shell. Output "
                "streams as it is produced; the working directory and "
                "environment persist across calls to the same shell_id."
            ),
            parameters={
                "type": "object",
                "properties": {
                    "command": {"type": "string"},
                    "shell_id": {"type": "string"},
                    "timeout": {"type": "number", "default": 30},
                },
                "required": ["command"],
            },
            sandbox=sandbox,
            health_timeout_s=SHELL_HEALTH_TIMEOUT_S,
        ),
    ]


def notebook_tools(sandbox: Optional[Sandbox] = None) -> List[SandboxTool]:
    """`notebook_run_cell` (reference server_tools/notebook.py)."""
    return [
        SandboxTool(
            name="notebook_run_cell",
            description=(
                "Execute a Python cell in the sandbox's persistent notebook "
                "kernel. Variables persist across cells; the value of a "
                "trailing expression is echoed."
            ),
            parameters={
                "type": "object",
                "properties": {
                    "code": {"type": "string"},
                    "kernel_id": {"type": "string"},
                    "timeout": {"type": "number", "default": 300},
                },
                "required": ["code"],
            },
            sandbox=sandbox,
            health_timeout_s=NOTEBOOK_HEALTH_TIMEOUT_S,
        ),
    ]


def sandbox_builtin_tools(sandbox_url: str) -> List[SandboxTool]:
    """Shell + notebook tools bound to a URL-direct sandbox."""
    from .local import LocalSandbox

    sandbox = LocalSandbox(sandbox_url)
    return [*shell_tools(sandbox), *notebook_tools(sandbox)]
