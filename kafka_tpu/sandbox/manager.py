"""SandboxManager — thread↔sandbox lifecycle.

Parity: reference src/sandbox/manager.py:37-458 —
  * non-blocking `get_sandbox_if_ready` with a ready cache and
    claim-if-unclaimed reconciliation (:149-205);
  * `ensure_sandbox_background` spawning a creation task, deduped by a
    pending set (:252-314);
  * the three-case lifecycle: new→create, healthy→reuse, dead→restart
    (:316-377), with the warm-pool fast path (:388-400);
  * claim-config builder injecting THREAD_ID / VM API key / env (:85-147);
  * `release_sandbox` (:445-458).

Construction policy is delegated to a `SandboxFactory` (process-spawned
local sandboxes in this tree; a cloud factory implements the same
protocol).  One reference bug fixed: `_ready_sandboxes` was mutated from
background tasks without coordination (SURVEY §5.2) — here all cache
mutation happens on the event loop (no threads), and the pending-set
discipline is enforced with try/finally.
"""

from __future__ import annotations

import abc
import asyncio
import logging
from typing import Any, Dict, Optional

from ..db.base import DBClient
from .base import Sandbox
from .types import SandboxConfig, SandboxError
from .warm import WarmSandboxFactory

logger = logging.getLogger("kafka_tpu.sandbox.manager")

RESTART_GRACE_S = 60.0  # reference manager.py: 60s grace before declaring dead


async def _aclose_quiet(sandbox: Sandbox) -> None:
    """Close a dropped sandbox handle without letting close errors mask
    the drop decision (each handle owns an httpx client)."""
    aclose = getattr(sandbox, "aclose", None)
    if aclose is not None:
        try:
            await aclose()
        except Exception:
            logger.debug("sandbox aclose failed", exc_info=True)


class SandboxFactory(abc.ABC):
    """Provisioning policy: how sandboxes are created/found/restarted."""

    @abc.abstractmethod
    async def create(self, thread_id: str) -> Sandbox: ...

    @abc.abstractmethod
    async def connect(self, sandbox_id: str) -> Optional[Sandbox]:
        """Re-attach to an existing sandbox; None if it no longer exists."""

    async def restart(self, sandbox_id: str) -> Optional[Sandbox]:
        """Restart a dead sandbox in place; None if impossible."""
        return None

    async def terminate(self, sandbox_id: str) -> None: ...

    async def aclose(self) -> None: ...


class SandboxManager:
    def __init__(
        self,
        db: DBClient,
        factory: SandboxFactory,
        warm_factory: Optional[WarmSandboxFactory] = None,
        extra_claim_env: Optional[Dict[str, str]] = None,
        live_timeout_s: float = 300.0,
    ):
        self.db = db
        self.factory = factory
        self.warm_factory = warm_factory
        self.extra_claim_env = dict(extra_claim_env or {})
        self.live_timeout_s = live_timeout_s
        self._ready: Dict[str, Sandbox] = {}  # thread_id -> live sandbox
        self._pending: set = set()  # thread_ids with creation in flight
        self._tasks: Dict[str, asyncio.Task] = {}
        # fire-and-forget cleanup tasks: the loop only weak-refs tasks, so
        # hold them here until done or GC can collect one mid-await
        self._bg_tasks: set = set()
        # Crash supervision hookup (ProcessSandboxFactory exit watcher):
        # when a subprocess dies, evict the ready-cache entry immediately
        # rather than on the next health probe — in-flight tool execs get
        # their one terminal error from the broken stream, and the next
        # get_sandbox_if_ready goes straight to the reconnect/restart path
        # instead of serving a dead handle out of cache.
        register = getattr(factory, "set_crash_listener", None)
        if register is not None:
            register(self._on_sandbox_crash)

    def _on_sandbox_crash(self, sandbox_id: str) -> None:
        """Factory exit-watcher callback (runs on the event loop — all
        cache mutation stays loop-confined, the module invariant)."""
        for thread_id, sandbox in list(self._ready.items()):
            if sandbox.sandbox_id == sandbox_id:
                logger.warning(
                    "sandbox %s for thread %s crashed; evicting from "
                    "ready cache", sandbox_id, thread_id,
                )
                self._ready.pop(thread_id, None)
                task = asyncio.get_running_loop().create_task(
                    _aclose_quiet(sandbox)
                )
                self._bg_tasks.add(task)
                task.add_done_callback(self._bg_tasks.discard)

    # -- claim config (reference manager.py:85-147) --------------------

    async def build_claim_config(self, thread_id: str) -> SandboxConfig:
        vm_key = await self.db.get_or_create_vm_api_key(thread_id)
        env = {"THREAD_ID": thread_id, "VM_API_KEY": vm_key}
        cfg = await self.db.get_thread_config(thread_id) or {}
        if cfg.get("memory_dsn"):
            env["MEMORY_DSN"] = str(cfg["memory_dsn"])
        env.update(self.extra_claim_env)
        return SandboxConfig(thread_id=thread_id, vm_api_key=vm_key, env=env)

    # -- non-blocking readiness (reference manager.py:149-205) ---------

    async def get_sandbox_if_ready(self, thread_id: str) -> Optional[Sandbox]:
        """Return a healthy, claimed sandbox for the thread, or None
        without blocking on creation."""
        sandbox = self._ready.get(thread_id)
        if sandbox is not None:
            status = await sandbox.check_health()
            if status.get("healthy"):
                if not status.get("claimed"):
                    # claim reconciliation: re-claim with fresh config; a
                    # failure means someone else claimed it in the gap —
                    # drop it from the cache rather than serve a sandbox
                    # whose tools will be rejected
                    ok = await sandbox.claim(
                        await self.build_claim_config(thread_id)
                    )
                    if not ok:
                        logger.warning(
                            "re-claim failed for %s; dropping", thread_id
                        )
                        self._ready.pop(thread_id, None)
                        await _aclose_quiet(sandbox)
                        return None
                return sandbox
            logger.warning("cached sandbox for %s went unhealthy", thread_id)
            self._ready.pop(thread_id, None)
            await _aclose_quiet(sandbox)

        if thread_id in self._pending:
            return None

        # cold path: maybe a sandbox id is on record and still alive
        sandbox_id = await self.db.get_thread_sandbox_id(thread_id)
        if not sandbox_id:
            return None
        try:
            sandbox = await self.factory.connect(sandbox_id)
        except SandboxError as e:
            # transient control-plane failure on a POLLING path: report
            # "not ready yet" so LazySandbox keeps retrying until its
            # deadline — the binding (and the VM) must survive the blip
            logger.warning("connect for %s not ready: %s", thread_id, e)
            return None
        if sandbox is None:
            return None
        status = await sandbox.check_health()
        if not status.get("healthy"):
            await _aclose_quiet(sandbox)
            return None
        # Re-claim even when already claimed: a freshly connected client
        # must (re)learn the vm_api_key or its tool calls are rejected.
        # Same-thread re-claims presenting the key are idempotent
        # server-side; a False here means the sandbox belongs to someone
        # else (or the key rotated) — don't serve it. Close what we drop:
        # LazySandbox re-polls this path every 200ms and each miss would
        # otherwise leak a connected httpx client.
        if not await sandbox.claim(await self.build_claim_config(thread_id)):
            await _aclose_quiet(sandbox)
            return None
        self._ready[thread_id] = sandbox
        return sandbox

    # -- background creation (reference manager.py:252-314) ------------

    def ensure_sandbox_background(self, thread_id: str) -> None:
        """Fire-and-forget creation; deduped while one is in flight."""
        if thread_id in self._ready or thread_id in self._pending:
            return
        self._pending.add(thread_id)
        task = asyncio.get_running_loop().create_task(
            self._ensure_sandbox_task(thread_id)
        )
        self._tasks[thread_id] = task

    async def _ensure_sandbox_task(self, thread_id: str) -> None:
        sandbox: Optional[Sandbox] = None
        try:
            sandbox = await self._get_or_create(thread_id)
            await self.db.update_thread_sandbox_id(thread_id, sandbox.sandbox_id)
            await sandbox.wait_until_live(timeout=self.live_timeout_s)
            if not await sandbox.claim(await self.build_claim_config(thread_id)):
                raise SandboxError(
                    f"claim failed for thread {thread_id} on "
                    f"sandbox {sandbox.sandbox_id}"
                )
            self._ready[thread_id] = sandbox
            logger.info("sandbox %s ready for thread %s",
                        sandbox.sandbox_id, thread_id)
        except Exception:
            logger.exception("sandbox creation failed for thread %s", thread_id)
            if sandbox is not None:
                await _aclose_quiet(sandbox)
        finally:
            self._pending.discard(thread_id)
            self._tasks.pop(thread_id, None)

    async def ensure_sandbox(self, thread_id: str) -> Sandbox:
        """Blocking convenience: create/recover and wait until ready."""
        ready = await self.get_sandbox_if_ready(thread_id)
        if ready is not None:
            return ready
        if thread_id in self._pending:
            task = self._tasks.get(thread_id)
            if task is not None:
                await task
            sandbox = self._ready.get(thread_id)
            if sandbox is None:
                raise SandboxError(
                    f"sandbox creation failed for thread {thread_id}"
                )
            return sandbox
        self._pending.add(thread_id)
        try:
            sandbox = await self._get_or_create(thread_id)
            await self.db.update_thread_sandbox_id(thread_id, sandbox.sandbox_id)
            await sandbox.wait_until_live(timeout=self.live_timeout_s)
            if not await sandbox.claim(await self.build_claim_config(thread_id)):
                raise SandboxError(
                    f"claim failed for thread {thread_id} on "
                    f"sandbox {sandbox.sandbox_id}"
                )
            self._ready[thread_id] = sandbox
            return sandbox
        finally:
            self._pending.discard(thread_id)

    # -- three-case lifecycle (reference manager.py:316-377) -----------

    async def _get_or_create(self, thread_id: str) -> Sandbox:
        sandbox_id = await self.db.get_thread_sandbox_id(thread_id)
        if sandbox_id:
            sandbox = await self.factory.connect(sandbox_id)
            if sandbox is not None:
                status = await sandbox.check_health()
                if status.get("healthy"):
                    logger.info("reusing sandbox %s for %s",
                                sandbox_id, thread_id)
                    return sandbox
                restarted = await self.factory.restart(sandbox_id)
                if restarted is not None:
                    logger.info("restarted sandbox %s for %s",
                                sandbox_id, thread_id)
                    return restarted
            logger.info("sandbox %s is gone; creating fresh", sandbox_id)

        # warm-pool fast path (reference manager.py:388-400)
        if self.warm_factory is not None:
            warm_id = await self.warm_factory.claim_warm()
            if warm_id:
                sandbox = await self.factory.connect(warm_id)
                if sandbox is not None:
                    logger.info("claimed warm sandbox %s for %s",
                                warm_id, thread_id)
                    return sandbox

        return await self.factory.create(thread_id)

    # -- teardown ------------------------------------------------------

    async def release_sandbox(self, thread_id: str, terminate: bool = False) -> None:
        sandbox = self._ready.pop(thread_id, None)
        if sandbox is None:
            return
        try:
            if terminate:
                await self.factory.terminate(sandbox.sandbox_id)
                await self.db.update_thread_sandbox_id(thread_id, None)
            else:
                await sandbox.reset()
        except Exception as e:
            logger.warning("release failed for %s: %s", thread_id, e)

    async def aclose(self) -> None:
        for task in list(self._tasks.values()):
            task.cancel()
        self._ready.clear()
        await self.factory.aclose()
