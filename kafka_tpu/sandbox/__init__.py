"""Sandbox tier: tool-execution runtimes behind the sandbox HTTP protocol.

Client side: Sandbox ABC, LocalSandbox (HTTP/SSE), LazySandbox,
SandboxManager + factories (subprocess, warm pools).
Server side: sandbox/server.py — the in-tree sandbox implementation
(shell sessions, notebook kernels) the factories spawn.
"""

from .base import Sandbox
from .lazy import LazySandbox
from .local import LocalSandbox
from .manager import SandboxFactory, SandboxManager
from .process import ProcessSandboxFactory
from .remote import RemoteSandboxFactory
from .tools import (
    SandboxTool,
    notebook_tools,
    sandbox_builtin_tools,
    shell_tools,
)
from .types import SandboxConfig, SandboxError, SandboxInfo, SandboxState
from .warm import HTTPWarmSandboxFactory, ProcessWarmPool, WarmSandboxFactory

__all__ = [
    "HTTPWarmSandboxFactory",
    "LazySandbox",
    "LocalSandbox",
    "ProcessSandboxFactory",
    "RemoteSandboxFactory",
    "ProcessWarmPool",
    "Sandbox",
    "SandboxConfig",
    "SandboxError",
    "SandboxFactory",
    "SandboxInfo",
    "SandboxManager",
    "SandboxState",
    "SandboxTool",
    "WarmSandboxFactory",
    "notebook_tools",
    "sandbox_builtin_tools",
    "shell_tools",
]
