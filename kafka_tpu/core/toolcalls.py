"""Tool-call delta accumulation and argument parsing.

OpenAI streaming emits tool calls as per-index deltas: the first delta for an
index carries id/name, later deltas append fragments to
`function.arguments`.  The accumulator reassembles them in index order.
Behavior parity: reference src/agents/base.py:285-331 (inline accumulation
inside the agent loop) — factored out here so the engine, agent loop, and
server can all share it.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


class ToolCallAccumulator:
    """Reassembles streamed tool-call deltas into complete tool calls."""

    def __init__(self) -> None:
        self._by_index: Dict[int, Dict[str, Any]] = {}

    def add_delta(self, delta: Dict[str, Any]) -> None:
        """Merge one tool-call delta (an element of `delta.tool_calls`)."""
        index = delta.get("index", 0)
        slot = self._by_index.setdefault(
            index,
            {"id": None, "type": "function", "function": {"name": "", "arguments": ""}},
        )
        if delta.get("id"):
            slot["id"] = delta["id"]
        if delta.get("type"):
            slot["type"] = delta["type"]
        fn = delta.get("function") or {}
        if fn.get("name"):
            slot["function"]["name"] = fn["name"]
        if fn.get("arguments"):
            slot["function"]["arguments"] += fn["arguments"]
        # Preserve provider-specific extras (e.g. opaque reasoning signatures
        # a provider needs echoed back on the next turn): unknown keys pass
        # through last-write-wins at both levels.
        for k, v in fn.items():
            if k not in ("name", "arguments"):
                slot["function"][k] = v
        for k, v in delta.items():
            if k not in ("index", "id", "type", "function"):
                slot[k] = v

    def add_deltas(self, deltas: Optional[List[Dict[str, Any]]]) -> None:
        for d in deltas or []:
            self.add_delta(d)

    @property
    def has_calls(self) -> bool:
        return bool(self._by_index)

    def result(self) -> List[Dict[str, Any]]:
        """Completed tool calls in index order (OpenAI wire shape)."""
        return [self._by_index[i] for i in sorted(self._by_index)]

    def clear(self) -> None:
        self._by_index.clear()


def parse_tool_arguments(call_or_arguments: Any) -> Dict[str, Any]:
    """Parse tool-call JSON arguments into a kwargs dict.

    Accepts either a completed OpenAI tool-call dict (detected by its
    `function` key; uses `function.arguments`) or raw arguments (a JSON
    string, an already-parsed dict, or None).  Empty/whitespace -> {}.
    Malformed JSON -> {"_raw": raw} so the unparseable text is preserved for
    error reporting rather than dropped.  Non-dict JSON (e.g. a bare list)
    -> {"_value": parsed}.
    """
    raw = call_or_arguments
    if isinstance(raw, dict):
        if "function" in raw:
            raw = (raw.get("function") or {}).get("arguments") or ""
        else:
            return raw  # already a parsed arguments dict
    if raw is None or not str(raw).strip():
        return {}
    try:
        parsed = json.loads(raw)
    except (json.JSONDecodeError, TypeError):
        return {"_raw": raw}
    return parsed if isinstance(parsed, dict) else {"_value": parsed}


def make_tool_call(
    call_id: str, name: str, arguments: Any, index: Optional[int] = None
) -> Dict[str, Any]:
    """Build a complete OpenAI-wire tool call dict."""
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    tc: Dict[str, Any] = {
        "id": call_id,
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }
    if index is not None:
        tc["index"] = index
    return tc
