"""Core conversation types shared across the whole framework.

These are the wire-level primitives every layer speaks: messages in OpenAI
chat format, incremental stream chunks, and full completion responses.

Capability parity with the reference service's LLM type layer
(reference: src/llm/types.py:29-185), but implemented as slotted dataclasses
rather than pydantic models: these objects are created per-token on the
decode hot path of the TPU engine, where pydantic validation overhead is
measurable.  Pydantic is reserved for the HTTP boundary (core/wire.py).
"""

from __future__ import annotations

import enum
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


class Role(str, enum.Enum):
    """Message roles following the OpenAI convention."""

    SYSTEM = "system"
    USER = "user"
    ASSISTANT = "assistant"
    TOOL = "tool"


# content may be a plain string or OpenAI multi-part content
# (list of {"type": "text"|"image_url", ...} parts).
Content = Any


@dataclass(slots=True)
class Message:
    """A single conversation message in OpenAI chat format.

    Parity: reference src/llm/types.py:29 (Message).
    """

    role: str
    content: Optional[Content] = None
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    tool_call_id: Optional[str] = None
    # Opaque provider metadata carried through unmodified (the analog of the
    # reference's Gemini `thought_signature` passthrough, portkey.py:381-417).
    metadata: Optional[Dict[str, Any]] = None
    # Unknown TOP-LEVEL keys round-tripped verbatim: foreign providers put
    # opaque fields directly on the message (e.g. `thought_signature`,
    # portkey.py:282-287); dict -> Message -> dict must not strip them or
    # a passthrough deployment silently loses provider state across turns.
    extra: Optional[Dict[str, Any]] = None

    _KNOWN = ("role", "content", "name", "tool_calls", "tool_call_id",
              "metadata")

    def to_dict(self) -> Dict[str, Any]:
        """OpenAI-wire dict, omitting None fields (APIs reject nulls)."""
        d: Dict[str, Any] = {"role": self.role}
        if self.extra:
            for k, v in self.extra.items():
                if k not in self._KNOWN:
                    d[k] = v
        if self.content is not None:
            d["content"] = self.content
        if self.name is not None:
            d["name"] = self.name
        if self.tool_calls is not None:
            d["tool_calls"] = self.tool_calls
        if self.tool_call_id is not None:
            d["tool_call_id"] = self.tool_call_id
        if self.metadata is not None:
            d["metadata"] = self.metadata
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Message":
        extra = {k: v for k, v in d.items() if k not in cls._KNOWN}
        return cls(
            role=d["role"],
            content=d.get("content"),
            name=d.get("name"),
            tool_calls=d.get("tool_calls"),
            tool_call_id=d.get("tool_call_id"),
            metadata=d.get("metadata"),
            extra=extra or None,
        )

    def text(self) -> str:
        """Flatten content to plain text (joins multi-part text segments)."""
        if self.content is None:
            return ""
        if isinstance(self.content, str):
            return self.content
        parts = []
        for part in self.content:
            if isinstance(part, dict) and part.get("type") == "text":
                parts.append(part.get("text", ""))
        return "".join(parts)


@dataclass(slots=True)
class StreamChunk:
    """One incremental piece of a streaming completion.

    Parity: reference src/llm/types.py:71 (StreamChunk).
    finish_reason: None until final; then "stop" | "length" | "tool_calls".
    """

    content: Optional[str] = None
    role: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    finish_reason: Optional[str] = None
    model: Optional[str] = None
    id: Optional[str] = None
    # TPU-engine extras (absent in the reference, which proxied a remote API):
    token_ids: Optional[List[int]] = None
    usage: Optional[Dict[str, int]] = None

    @property
    def delta(self) -> str:
        return self.content or ""

    @property
    def is_final(self) -> bool:
        return self.finish_reason is not None

    def to_openai_dict(self, created: Optional[int] = None) -> Dict[str, Any]:
        """Render as an OpenAI chat.completion.chunk wire object.

        `id` must be set: every chunk of one stream must carry the same
        completion id (clients group chunks by it), so minting one here
        per-chunk would silently mis-group the stream.
        """
        if self.id is None:
            raise ValueError(
                "StreamChunk.id must be set before wire rendering; "
                "mint one per stream with new_completion_id()"
            )
        delta: Dict[str, Any] = {}
        if self.role is not None:
            delta["role"] = self.role
        if self.content is not None:
            delta["content"] = self.content
        if self.tool_calls is not None:
            delta["tool_calls"] = self.tool_calls
        out: Dict[str, Any] = {
            "id": self.id,
            "object": "chat.completion.chunk",
            "created": created if created is not None else int(time.time()),
            "model": self.model or "",
            "choices": [
                {"index": 0, "delta": delta, "finish_reason": self.finish_reason}
            ],
        }
        if self.usage is not None:
            out["usage"] = self.usage
        return out


@dataclass(slots=True)
class Usage:
    """Token accounting. The TPU engine reports real counts (the reference
    returned zeroed usage on the agent path, src/kafka/types.py:93-97)."""

    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0
    # Engine extras
    cached_prompt_tokens: int = 0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "prompt_tokens": self.prompt_tokens,
            "completion_tokens": self.completion_tokens,
            "total_tokens": self.total_tokens,
        }
        if self.cached_prompt_tokens:
            d["prompt_tokens_details"] = {"cached_tokens": self.cached_prompt_tokens}
        return d


@dataclass(slots=True)
class CompletionResponse:
    """Full non-streaming completion result.

    Parity: reference src/llm/types.py:113 (CompletionResponse).
    """

    content: Optional[str] = None
    role: str = "assistant"
    finish_reason: Optional[str] = None
    model: Optional[str] = None
    id: Optional[str] = None
    usage: Optional[Dict[str, int]] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None

    def to_message(self) -> Message:
        return Message(role=self.role, content=self.content, tool_calls=self.tool_calls)

    def to_openai_dict(self, created: Optional[int] = None) -> Dict[str, Any]:
        msg: Dict[str, Any] = {"role": self.role, "content": self.content}
        if self.tool_calls:
            msg["tool_calls"] = self.tool_calls
        return {
            "id": self.id or new_completion_id(),
            "object": "chat.completion",
            "created": created if created is not None else int(time.time()),
            "model": self.model or "",
            "choices": [
                {"index": 0, "message": msg, "finish_reason": self.finish_reason or "stop"}
            ],
            "usage": self.usage or Usage().to_dict(),
        }


class LLMProviderError(Exception):
    """Base error for LLM providers (parity: src/llm/types.py:160)."""

    def __init__(
        self,
        message: str,
        status_code: Optional[int] = None,
        provider: Optional[str] = None,
        original_error: Optional[Exception] = None,
    ):
        super().__init__(message)
        self.message = message
        self.status_code = status_code
        self.provider = provider
        self.original_error = original_error

    def __str__(self) -> str:
        parts = [self.message]
        if self.provider:
            parts.insert(0, f"[{self.provider}]")
        if self.status_code:
            parts.append(f"(status: {self.status_code})")
        return " ".join(parts)


class ContextLengthError(LLMProviderError):
    """Raised by the TPU engine when a prompt exceeds the model context.

    The reference could only detect this *after* a remote API rejected the
    request, by string-matching error text (context_compaction/base.py:10-65).
    The local engine counts tokens itself and raises this typed error
    pre-flight; the string form stays compatible with the reference's
    classifier patterns so both detection paths work.
    """

    def __init__(self, prompt_tokens: int, max_context: int, provider: str = "tpu"):
        super().__init__(
            f"prompt is too long: {prompt_tokens} tokens > {max_context} maximum "
            f"(context_length_exceeded)",
            status_code=400,
            provider=provider,
        )
        self.prompt_tokens = prompt_tokens
        self.max_context = max_context


class ServerOverloadedError(LLMProviderError):
    """Admission rejected: the engine's bounded waiting queue is full.

    Maps to HTTP 429 with a Retry-After header derived from current
    decode throughput (engine.retry_after_estimate).  Raised by the
    serving-side admission gate (server/app.py) and by the provider when
    the engine-thread backstop rejects a submit that raced past the gate.
    """

    def __init__(self, retry_after_s: float = 5.0, provider: str = "tpu",
                 message: Optional[str] = None):
        super().__init__(
            message or (
                "server overloaded: request queue is full, retry in "
                f"~{retry_after_s:.0f}s (server_overloaded)"
            ),
            status_code=429,
            provider=provider,
        )
        self.retry_after_s = float(retry_after_s)


class UnsupportedContentError(LLMProviderError):
    """A request carries content parts the served model cannot consume.

    The reference forwarded image parts through the gateway to multimodal
    models, pruning down to the newest 19 (src/llm/portkey.py:276,
    src/llm/utils.py:85-130).  The local TPU engine serves text-only
    checkpoints; silently flattening images to placeholders would let the
    model answer as if it had seen them, so the provider rejects loudly
    with this typed 400 instead (VERDICT r3 "serve or reject" decision:
    reject until a vision-capable model path exists).
    """

    def __init__(self, n_parts: int, kind: str = "image",
                 provider: str = "tpu"):
        super().__init__(
            f"conversation contains {n_parts} {kind} content part(s) but "
            f"the served model is text-only (unsupported_content); remove "
            f"them or serve a vision-capable checkpoint",
            status_code=400,
            provider=provider,
        )
        self.kind = kind
        self.n_parts = n_parts


def new_completion_id() -> str:
    return f"chatcmpl-{uuid.uuid4().hex[:24]}"


def new_tool_call_id() -> str:
    return f"call_{uuid.uuid4().hex[:24]}"
