"""Pure-Python conversation core: types, wire schemas, sanitization."""

from .types import (
    CompletionResponse,
    ContextLengthError,
    LLMProviderError,
    Message,
    Role,
    StreamChunk,
    Usage,
    new_completion_id,
    new_tool_call_id,
)
from .sanitize import (
    convert_to_internal_message,
    dicts_to_messages,
    find_safe_split_point,
    messages_to_dict_list,
    sanitize_messages_for_openai,
    validate_message_structure,
)
from .toolcalls import ToolCallAccumulator, make_tool_call, parse_tool_arguments

__all__ = [
    "CompletionResponse",
    "ContextLengthError",
    "LLMProviderError",
    "Message",
    "Role",
    "StreamChunk",
    "Usage",
    "new_completion_id",
    "new_tool_call_id",
    "convert_to_internal_message",
    "dicts_to_messages",
    "find_safe_split_point",
    "messages_to_dict_list",
    "sanitize_messages_for_openai",
    "validate_message_structure",
    "ToolCallAccumulator",
    "make_tool_call",
    "parse_tool_arguments",
]
