"""Message sanitization for OpenAI-wire compatibility.

Behavior parity with the reference's sanitizer (src/kafka/utils.py:25-61)
and structural validator (src/llm/context_compaction/base.py:115-168):

* every `tool` message must answer a tool_call in the *most recent*
  assistant-with-tool_calls message; orphans are dropped;
* a tool_call_id may be consumed at most once;
* any non-tool message that is not an assistant-with-tool_calls resets the
  window of valid ids;
* empty assistant messages (no content, no tool_calls) are dropped by the
  structural validator.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from .types import Message

logger = logging.getLogger("kafka_tpu.core.sanitize")


def convert_to_internal_message(chat_msg: Any) -> Message:
    """Convert an OpenAI-format message (pydantic model or dict) to Message."""
    if isinstance(chat_msg, dict):
        return Message.from_dict(chat_msg)
    return Message(
        role=chat_msg.role,
        content=chat_msg.content,
        name=getattr(chat_msg, "name", None),
        tool_calls=getattr(chat_msg, "tool_calls", None),
        tool_call_id=getattr(chat_msg, "tool_call_id", None),
    )


def sanitize_messages_for_openai(messages: List[Message]) -> List[Message]:
    """Drop tool messages that don't answer a live tool_call.

    Scans forward keeping a window of tool_call_ids opened by the latest
    assistant-with-tool_calls message; each id may be used once.
    """
    if not messages:
        return messages

    sanitized: List[Message] = []
    open_ids: set = set()

    for msg in messages:
        if msg.role == "assistant" and msg.tool_calls:
            open_ids = {tc.get("id") for tc in msg.tool_calls if tc.get("id")}
            sanitized.append(msg)
        elif msg.role == "tool":
            if msg.tool_call_id and msg.tool_call_id in open_ids:
                open_ids.discard(msg.tool_call_id)
                sanitized.append(msg)
            else:
                logger.warning(
                    "skipping orphan tool message (tool_call_id=%s name=%s)",
                    msg.tool_call_id,
                    msg.name,
                )
        else:
            open_ids = set()
            sanitized.append(msg)

    return sanitized


def validate_message_structure(
    messages: List[Dict[str, Any]],
    logger_: Optional[logging.Logger] = None,
) -> List[Dict[str, Any]]:
    """Validate/fix a dict-form message list after compaction surgery.

    Unlike the forward-scanning sanitizer above, this collects tool_call_ids
    from *all* assistant messages first (compaction may have reordered
    context), then drops orphan tool results and empty assistant messages.
    Parity: src/llm/context_compaction/base.py:115-168.
    """
    if not messages:
        return messages
    log = logger_ or logger

    valid_ids = {
        tc["id"]
        for msg in messages
        if msg.get("role") == "assistant" and msg.get("tool_calls")
        for tc in msg["tool_calls"]
        if tc.get("id")
    }

    validated: List[Dict[str, Any]] = []
    for msg in messages:
        if msg.get("role") == "tool" and msg.get("tool_call_id") not in valid_ids:
            log.warning("removing orphaned tool result id=%s", msg.get("tool_call_id"))
            continue
        if (
            msg.get("role") == "assistant"
            and not msg.get("content")
            and not msg.get("tool_calls")
        ):
            log.warning("removing empty assistant message")
            continue
        validated.append(msg)
    return validated


def find_safe_split_point(messages: List[Dict[str, Any]], target_split: int) -> int:
    """Largest index <= target_split that does not sever a tool exchange.

    A split is unsafe if it separates an assistant-with-tool_calls message
    from the tool results that answer it; in that case walk backwards until
    the boundary no longer cuts through a tool sequence.
    Parity: src/llm/context_compaction/base.py:68-112.
    """
    if target_split <= 0:
        return 0
    n = len(messages)
    if target_split >= n:
        return n

    split = target_split
    while split > 0:
        prev = messages[split - 1]
        nxt = messages[split] if split < n else None
        if prev.get("role") == "assistant" and prev.get("tool_calls"):
            split -= 1
            continue
        if nxt is not None and nxt.get("role") == "tool":
            split -= 1
            continue
        break
    return split


def messages_to_dict_list(messages: List[Message]) -> List[Dict[str, Any]]:
    return [m.to_dict() for m in messages]


def dicts_to_messages(dicts: List[Dict[str, Any]]) -> List[Message]:
    return [Message.from_dict(d) for d in dicts]
