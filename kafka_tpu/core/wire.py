"""OpenAI-compatible HTTP wire schemas (pydantic).

These live only at the HTTP boundary; internal layers use the dataclasses in
core/types.py.  Capability parity: reference src/kafka/types.py:13-107, plus
engine-specific extensions (seed, tools, response_format, logprobs) the
reference forwarded blindly to its remote gateway but the local TPU engine
implements itself.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

from pydantic import BaseModel, Field


class ChatMessage(BaseModel):
    """OpenAI-compatible message in requests.

    extra="allow": opaque provider fields placed directly on a message
    (the reference's Gemini `thought_signature`, portkey.py:282-287) must
    survive request parsing — Message.from_dict/to_dict round-trips them
    and the thread store persists them.
    """

    model_config = {"extra": "allow"}

    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    tool_call_id: Optional[str] = None


class ChatCompletionRequest(BaseModel):
    """OpenAI-compatible chat completion request.

    On the thread endpoints, `messages` carries only the NEW message(s); the
    server prepends the stored thread history.
    """

    model: str = Field(..., description="Model ID to use")
    messages: List[ChatMessage]
    temperature: Optional[float] = Field(None, ge=0, le=2)
    max_tokens: Optional[int] = Field(None, gt=0)
    stream: Optional[bool] = False
    stop: Optional[Union[str, List[str]]] = None
    top_p: Optional[float] = Field(None, ge=0, le=1)
    top_k: Optional[int] = Field(None, ge=0)
    frequency_penalty: Optional[float] = Field(None, ge=-2, le=2)
    presence_penalty: Optional[float] = Field(None, ge=-2, le=2)
    seed: Optional[int] = None
    user: Optional[str] = None
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    response_format: Optional[Dict[str, Any]] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = Field(None, ge=0, le=20)
    stream_options: Optional[Dict[str, Any]] = None


class AgentRunRequest(BaseModel):
    """Request body for the agent-run endpoints."""

    messages: List[ChatMessage]
    model: str = "llama-3.2-1b"
    temperature: float = 0.7
    max_tokens: Optional[int] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None


class CreateThreadRequest(BaseModel):
    system_message: Optional[str] = None
    user_id: Optional[str] = None
    kafka_profile_id: Optional[str] = None
    metadata: Optional[Dict[str, Any]] = None


class DeltaContent(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None


class StreamChoice(BaseModel):
    index: int = 0
    delta: DeltaContent
    finish_reason: Optional[str] = None


class StreamChunkResponse(BaseModel):
    id: str
    object: str = "chat.completion.chunk"
    created: int
    model: str
    choices: List[StreamChoice]


class MessageContent(BaseModel):
    role: str = "assistant"
    content: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None


class Choice(BaseModel):
    index: int = 0
    message: MessageContent
    finish_reason: Optional[str] = None


class UsageModel(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ChatCompletionResponse(BaseModel):
    id: str
    object: str = "chat.completion"
    created: int
    model: str
    choices: List[Choice]
    usage: Optional[UsageModel] = None
