"""Client-side SSE reconstruction — the reference playground's consumer
contract, as a reusable Python implementation.

The serving protocol (server/sse.py) emits four event kinds over one SSE
stream: OpenAI chat chunks, streaming ``tool_result`` deltas, a
``tool_messages`` batch, and ``agent_done`` (plus ``error``), terminated by
``data: [DONE]``.  The reference's Next.js playground reconstructs a chat
transcript from that stream (playground/src/app/page.tsx:127-320); this
module implements the same reconstruction rules so that:

* examples and tests can consume the live stream exactly the way the real
  frontend does (the contract is *proved*, not assumed), and
* the in-tree playground (server/playground.html) mirrors this logic in JS.

Reconstruction rules (the page.tsx contract):

* OpenAI chunks accumulate into the trailing assistant message; a chunk id
  different from the current completion id starts a NEW assistant message
  (per-completion segmentation — one agent iteration per completion id).
* ``delta.tool_calls`` entries accumulate by ``index``: id and name
  overwrite, ``function.arguments`` string-concatenates.
* ``tool_result`` deltas append to the tool message with the same
  ``tool_call_id``, creating it (followed by a fresh empty assistant
  message) on first delta.
* ``tool_messages`` replaces the prior tool/assistant-with-tool-calls
  messages with the server's canonical batch (the durable form), again
  followed by a fresh empty assistant message.
* ``agent_done`` drops a trailing empty assistant message.
* ``[DONE]`` ends the stream.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional


class SSEMessageReconstructor:
    """Feed SSE lines (or whole payloads); read `.messages` at any point."""

    def __init__(self) -> None:
        self.messages: List[Dict[str, Any]] = []
        self.done = False
        self.errors: List[Dict[str, Any]] = []
        self._completion_id: Optional[str] = None
        self._content: List[str] = []
        self._tool_calls: Dict[int, Dict[str, str]] = {}

    # -- feeding --------------------------------------------------------

    def feed_line(self, line: str) -> None:
        line = line.rstrip("\r\n")
        if not line.startswith("data: "):
            return
        payload = line[len("data: "):]
        if payload == "[DONE]":
            self.done = True
            return
        try:
            event = json.loads(payload)
        except json.JSONDecodeError:
            return
        self.feed_event(event)

    def feed_text(self, text: str) -> None:
        for line in text.splitlines():
            self.feed_line(line)

    def feed_lines(self, lines: Iterable[str]) -> None:
        for line in lines:
            self.feed_line(line)

    # -- event handling (page.tsx:127-320 semantics) --------------------

    def feed_event(self, event: Dict[str, Any]) -> None:
        etype = event.get("type")
        if etype == "agent_done":
            self._drop_trailing_empty_assistant(require_no_tool_calls=True)
            return
        if etype == "error":
            self.errors.append(event)
            return
        if etype == "tool_result":
            self._on_tool_result(event)
            return
        if etype == "tool_messages" and event.get("messages"):
            self._on_tool_messages(event["messages"])
            return
        choice = (event.get("choices") or [None])[0]
        if choice and choice.get("delta") is not None:
            self._on_chunk(event, choice)

    # -- handlers -------------------------------------------------------

    def _last(self) -> Optional[Dict[str, Any]]:
        return self.messages[-1] if self.messages else None

    def _drop_trailing_empty_assistant(
        self, require_no_tool_calls: bool = False
    ) -> None:
        last = self._last()
        if (
            last is not None
            and last.get("role") == "assistant"
            and not last.get("content")
            and (not require_no_tool_calls or not last.get("tool_calls"))
        ):
            self.messages.pop()

    def _on_tool_result(self, event: Dict[str, Any]) -> None:
        tcid = event.get("tool_call_id")
        for msg in self.messages:
            if msg.get("role") == "tool" and msg.get("tool_call_id") == tcid:
                msg["content"] = (msg.get("content") or "") + (
                    event.get("delta") or ""
                )
                return
        # first delta for this call: drop a bare trailing assistant stub,
        # add the tool message, restart an assistant message after it
        last = self._last()
        if (
            last is not None
            and last.get("role") == "assistant"
            and not last.get("content")
            and not last.get("tool_calls")
        ):
            self.messages.pop()
        self.messages.append({
            "role": "tool",
            "content": event.get("delta") or "",
            "tool_call_id": tcid,
            "name": event.get("tool_name"),
        })
        self.messages.append({"role": "assistant", "content": ""})

    def _on_tool_messages(self, batch: List[Dict[str, Any]]) -> None:
        self._drop_trailing_empty_assistant()
        kept = [
            m for m in self.messages
            if not (
                m.get("role") == "tool"
                or (m.get("role") == "assistant" and m.get("tool_calls"))
            )
        ]
        self.messages = kept + list(batch) + [
            {"role": "assistant", "content": ""}
        ]

    def _on_chunk(self, event: Dict[str, Any], choice: Dict[str, Any]) -> None:
        delta = choice.get("delta") or {}
        chunk_id = event.get("id")
        if chunk_id and chunk_id != self._completion_id:
            if self._completion_id is not None:
                # new agent iteration: reset accumulators; keep the previous
                # assistant message if it holds anything
                self._content = []
                self._tool_calls = {}
                last = self._last()
                if (
                    last is not None
                    and last.get("role") == "assistant"
                    and (last.get("content") or last.get("tool_calls"))
                ):
                    self.messages.append({"role": "assistant", "content": ""})
            self._completion_id = chunk_id

        if self._last() is None or self._last().get("role") != "assistant":
            self.messages.append({"role": "assistant", "content": ""})

        if delta.get("tool_calls"):
            for tc in delta["tool_calls"]:
                idx = tc.get("index", 0)
                acc = self._tool_calls.setdefault(
                    idx, {"id": "", "name": "", "arguments": ""}
                )
                if tc.get("id"):
                    acc["id"] = tc["id"]
                fn = tc.get("function") or {}
                if fn.get("name"):
                    acc["name"] = fn["name"]
                if fn.get("arguments"):
                    acc["arguments"] += fn["arguments"]
            self._last()["tool_calls"] = self._tool_calls_list()

        if delta.get("content"):
            self._content.append(delta["content"])
            self._last()["content"] = "".join(self._content)

        if choice.get("finish_reason") == "tool_calls":
            last = self._last()
            last["content"] = last.get("content") or None
            last["tool_calls"] = self._tool_calls_list()

    def _tool_calls_list(self) -> List[Dict[str, Any]]:
        return [
            {
                "id": acc["id"],
                "type": "function",
                "function": {"name": acc["name"],
                             "arguments": acc["arguments"]},
            }
            for acc in self._tool_calls.values()
        ]
