"""AgentToolProvider — routes tool execution by source.

Parity: reference src/tools/agent.py:416-833. Sources:
  * "local"   — in-process `Tool` handlers (sync/async/async-gen);
  * "sandbox" — `SandboxTool`s forwarding to a sandbox VM (sandbox tier);
  * "mcp"     — tools discovered from MCP servers (tools/mcp.py).

All are registered into one namespace; `get_tools` returns the merged
OpenAI-format list and `run_tool_stream` dispatches to the owner.  Unknown
tools yield a terminal error event (the model sees the failure and can
correct itself) rather than raising — an agent run must survive a bad tool
name, matching the reference's behavior.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence

from .. import tracing
from ..failpoints import failpoint
from .base import ToolProvider
from .types import MCPServerConfig, Tool, ToolEvent, parse_tool_arguments

logger = logging.getLogger("kafka_tpu.tools")


class AgentToolProvider(ToolProvider):
    def __init__(
        self,
        tools: Optional[Sequence[Tool]] = None,
        mcp_servers: Optional[Sequence[MCPServerConfig]] = None,
        on_tool_complete: Optional[Any] = None,
    ):
        self._tools: Dict[str, Tool] = {}
        for t in tools or []:
            self.register_tool(t)
        self._mcp_configs = list(mcp_servers or [])
        self._mcp_connections: List[Any] = []  # MCPConnection, tools/mcp.py
        self._connected = False
        # ISSUE 20: fired with (tool_name, tool_call_id) on each tool's
        # terminal event — the sandbox SSE stream's completion — so a
        # serving tier can kick the thread's expected-return hint (wake
        # prefetch) without waiting for the agent loop to come around.
        # Must never raise into the event stream.
        self.on_tool_complete = on_tool_complete

    # -- registry ------------------------------------------------------

    def register_tool(self, tool: Tool) -> None:
        if tool.name in self._tools:
            logger.warning("tool %s re-registered (overriding)", tool.name)
        self._tools[tool.name] = tool

    def unregister_tool(self, name: str) -> None:
        self._tools.pop(name, None)

    def get_tool(self, name: str) -> Optional[Tool]:
        return self._tools.get(name)

    # -- lifecycle -----------------------------------------------------

    async def connect(self) -> None:
        """Connect MCP servers; failures are logged and skipped (an
        unreachable tool server must not take down serving — reference
        src/tools/agent.py:494-496)."""
        if self._connected:
            return
        if self._mcp_configs:
            from .mcp import MCPConnection

            for cfg in self._mcp_configs:
                conn = MCPConnection(cfg)
                try:
                    await conn.connect()
                except Exception as e:
                    logger.warning(
                        "MCP server %s failed to connect: %s — skipping",
                        cfg.name, e,
                    )
                    continue
                self._mcp_connections.append(conn)
                for tool in conn.discovered_tools():
                    self.register_tool(tool)
        self._connected = True

    async def disconnect(self) -> None:
        for conn in self._mcp_connections:
            try:
                await conn.disconnect()
            except Exception as e:
                logger.warning("MCP disconnect failed: %s", e)
        self._mcp_connections.clear()
        self._connected = False

    # -- execution -----------------------------------------------------

    def get_tools(self) -> List[Dict[str, Any]]:
        return [t.to_openai() for t in self._tools.values()]

    async def run_tool_stream(
        self,
        name: str,
        arguments: Any,
        tool_call_id: Optional[str] = None,
    ) -> AsyncIterator[ToolEvent]:
        tool = self._tools.get(name)
        if tool is None:
            yield ToolEvent(
                "error",
                f"unknown tool: {name}. Available: {sorted(self._tools)}",
                tool_name=name,
                tool_call_id=tool_call_id,
            )
            self._notify_complete(name, tool_call_id)
            return
        args = parse_tool_arguments(arguments)
        # injected tool latency/faults (agent-gap benches arm
        # `agent.tool=delay(...)` to model a slow tool without a real
        # sandbox round trip)
        try:
            failpoint("agent.tool")
        except Exception as e:
            yield ToolEvent(
                "error", f"tool fault injected: {e}",
                tool_name=name, tool_call_id=tool_call_id,
            )
            self._notify_complete(name, tool_call_id)
            return
        # one span per tool call; sandbox tools propagate the resulting
        # context over the wire so child spans recorded INSIDE the sandbox
        # subprocess stitch back under this one (sandbox/local.py)
        with tracing.span(
            "tool.exec", attrs={"tool": name, "source": tool.source}
        ) as s:
            async for ev in tool.run_stream(args):
                ev.tool_call_id = tool_call_id
                ev.tool_name = ev.tool_name or name
                if s is not None and ev.kind == "error":
                    s.attrs["error"] = True
                yield ev
        self._notify_complete(name, tool_call_id)

    def _notify_complete(
        self, name: str, tool_call_id: Optional[str]
    ) -> None:
        """Terminal-event listener dispatch: a hint, never a failure."""
        cb = self.on_tool_complete
        if cb is None:
            return
        try:
            cb(name, tool_call_id)
        except Exception:
            logger.exception("on_tool_complete listener failed")
