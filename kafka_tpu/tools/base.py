"""ToolProvider ABC — the contract the agent loop executes tools through.

Parity: reference src/tools/base.py:73-245 (`connect/disconnect/get_tools/
run_tool`) plus the streaming entry `run_tool_stream` the reference added on
its concrete provider (src/tools/agent.py:677).  Streaming is part of the
ABC here: the TPU serving path treats streamed tool output as first-class
(it rides the same SSE channel as tokens).
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator, Dict, List, Optional

from .types import Tool, ToolEvent


class ToolProvider(abc.ABC):
    """Source of tools for an agent run."""

    async def connect(self) -> None:
        """Establish connections (MCP servers, sandboxes). Idempotent."""

    async def disconnect(self) -> None:
        """Tear down connections. Idempotent."""

    @abc.abstractmethod
    def get_tools(self) -> List[Dict[str, Any]]:
        """Available tools in OpenAI function-calling format."""
        raise NotImplementedError

    @abc.abstractmethod
    def run_tool_stream(
        self,
        name: str,
        arguments: Any,
        tool_call_id: Optional[str] = None,
    ) -> AsyncIterator[ToolEvent]:
        """Execute a tool, yielding `ToolEvent`s; the last is terminal."""
        raise NotImplementedError

    async def run_tool(
        self,
        name: str,
        arguments: Any,
        tool_call_id: Optional[str] = None,
    ) -> Any:
        """Non-streaming execution; returns the terminal result value."""
        result: Any = None
        async for ev in self.run_tool_stream(name, arguments, tool_call_id):
            if ev.kind == "result":
                result = ev.data
            elif ev.kind == "error":
                raise RuntimeError(str(ev.data))
        return result

    def has_tool(self, name: str) -> bool:
        return any(
            t.get("function", {}).get("name") == name for t in self.get_tools()
        )

    async def __aenter__(self) -> "ToolProvider":
        await self.connect()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.disconnect()
