"""Native MCP (Model Context Protocol) client.

Parity: reference src/tools/agent.py:63-380 (MCPConnection — stdio +
streamable-HTTP with SSE fallback transports, tool discovery to OpenAI
format, streamed call results). The reference delegates the protocol to the
`mcp` PyPI package; this environment does not ship it, so the protocol is
implemented natively here: JSON-RPC 2.0 over

  * stdio            — newline-delimited JSON to a subprocess (MCP stdio
                       transport framing),
  * streamable-http  — POST per message; responses arrive as JSON or as a
                       text/event-stream; session continuity via the
                       Mcp-Session-Id header,
  * sse (fallback)   — legacy HTTP+SSE transport: GET opens the event
                       stream, the first `endpoint` event names the POST
                       URL, responses arrive on the stream.

Connect failures raise MCPClientError; AgentToolProvider catches and skips
(an unreachable tool server must never take down serving — reference
src/tools/agent.py:494-496).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional
from urllib.parse import urljoin

from .types import MCPServerConfig, Tool, ToolEvent

logger = logging.getLogger("kafka_tpu.tools.mcp")

PROTOCOL_VERSION = "2025-03-26"
CLIENT_INFO = {"name": "kafka-tpu", "version": "0.2.0"}


class MCPClientError(Exception):
    """Raised on transport/protocol failures talking to an MCP server."""


# ---------------------------------------------------------------------------
# Transports. Each exposes: start(), send(msg: dict), recv() -> dict, close().
# recv() yields every inbound JSON-RPC message (responses + notifications);
# the connection layer routes them.
# ---------------------------------------------------------------------------


class _StdioTransport:
    """MCP stdio framing: one JSON-RPC message per line on stdin/stdout."""

    def __init__(self, command: str, args: List[str], env: Dict[str, str]):
        self._command = command
        self._args = args
        self._env = env
        self._proc: Optional[asyncio.subprocess.Process] = None

    async def start(self) -> None:
        env = dict(os.environ)
        env.update(self._env)
        try:
            self._proc = await asyncio.create_subprocess_exec(
                self._command,
                *self._args,
                stdin=asyncio.subprocess.PIPE,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL,
                env=env,
            )
        except (OSError, ValueError) as e:
            raise MCPClientError(f"failed to spawn {self._command}: {e}")

    async def send(self, msg: Dict[str, Any]) -> None:
        proc = self._proc
        if proc is None or proc.stdin is None:
            raise MCPClientError("stdio transport not started")
        if proc.returncode is not None:
            raise MCPClientError(
                f"MCP server process exited (code {proc.returncode})"
            )
        proc.stdin.write(json.dumps(msg).encode() + b"\n")
        await proc.stdin.drain()

    async def recv(self) -> Dict[str, Any]:
        proc = self._proc
        if proc is None or proc.stdout is None:
            raise MCPClientError("stdio transport not started")
        while True:
            line = await proc.stdout.readline()
            if not line:
                raise MCPClientError("MCP server closed stdout")
            line = line.strip()
            if not line:
                continue
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                # servers may emit stray diagnostics on stdout; skip them
                logger.debug("skipping non-JSON stdio line: %r", line[:200])

    async def close(self) -> None:
        proc = self._proc
        self._proc = None
        if proc is None:
            return
        with contextlib.suppress(Exception):
            if proc.stdin:
                proc.stdin.close()
        if proc.returncode is None:
            with contextlib.suppress(Exception):
                proc.terminate()
            try:
                await asyncio.wait_for(proc.wait(), timeout=3.0)
            except (asyncio.TimeoutError, Exception):
                with contextlib.suppress(Exception):
                    proc.kill()
        # drop the pipe transports now, not at GC after the loop closes
        # (late GC raises "Event loop is closed" from transport __del__)
        with contextlib.suppress(Exception):
            proc._transport.close()  # type: ignore[attr-defined]


class _StreamableHTTPTransport:
    """MCP streamable-HTTP: POST each message; parse JSON or SSE replies."""

    def __init__(self, url: str, timeout: float = 30.0):
        self._url = url
        self._timeout = timeout
        self._client: Any = None
        self._session_id: Optional[str] = None
        self._inbox: asyncio.Queue = asyncio.Queue()

    async def start(self) -> None:
        import httpx

        self._client = httpx.AsyncClient(timeout=self._timeout)

    def _headers(self) -> Dict[str, str]:
        h = {
            "Content-Type": "application/json",
            "Accept": "application/json, text/event-stream",
        }
        if self._session_id:
            h["Mcp-Session-Id"] = self._session_id
        return h

    async def send(self, msg: Dict[str, Any]) -> None:
        if self._client is None:
            raise MCPClientError("http transport not started")
        try:
            resp = await self._client.post(
                self._url, json=msg, headers=self._headers()
            )
        except Exception as e:
            raise MCPClientError(f"POST {self._url} failed: {e}")
        sid = resp.headers.get("mcp-session-id")
        if sid:
            self._session_id = sid
        if resp.status_code in (202, 204):
            return  # notification accepted, no body
        if resp.status_code >= 400:
            raise MCPClientError(
                f"MCP server returned HTTP {resp.status_code}: "
                f"{resp.text[:300]}"
            )
        ctype = resp.headers.get("content-type", "")
        if "text/event-stream" in ctype:
            for data in _iter_sse_datas(resp.text):
                with contextlib.suppress(json.JSONDecodeError):
                    await self._inbox.put(json.loads(data))
        elif resp.content:
            try:
                body = resp.json()
            except json.JSONDecodeError:
                raise MCPClientError(
                    f"MCP server sent non-JSON body: {resp.text[:300]}"
                )
            if isinstance(body, list):
                for item in body:
                    await self._inbox.put(item)
            else:
                await self._inbox.put(body)

    async def recv(self) -> Dict[str, Any]:
        return await self._inbox.get()

    async def close(self) -> None:
        client, self._client = self._client, None
        if client is not None:
            with contextlib.suppress(Exception):
                await client.aclose()


class _SSETransport:
    """Legacy HTTP+SSE transport: GET stream + `endpoint` event for POSTs."""

    def __init__(self, url: str, timeout: float = 30.0):
        self._url = url
        self._timeout = timeout
        self._client: Any = None
        self._post_url: Optional[str] = None
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._reader_task: Optional[asyncio.Task] = None
        self._endpoint_ready = asyncio.Event()
        self._reader_error: Optional[Exception] = None

    async def start(self) -> None:
        import httpx

        self._client = httpx.AsyncClient(timeout=httpx.Timeout(self._timeout,
                                                               read=None))
        self._reader_task = asyncio.create_task(self._read_stream())
        try:
            await asyncio.wait_for(
                self._endpoint_ready.wait(), timeout=self._timeout
            )
        except asyncio.TimeoutError:
            await self.close()
            raise MCPClientError(
                f"SSE endpoint event not received from {self._url}"
                + (f" ({self._reader_error})" if self._reader_error else "")
            )
        if self._reader_error is not None:
            err = self._reader_error
            await self.close()
            raise MCPClientError(f"SSE stream failed: {err}")

    async def _read_stream(self) -> None:
        try:
            async with self._client.stream(
                "GET", self._url, headers={"Accept": "text/event-stream"}
            ) as resp:
                if resp.status_code >= 400:
                    raise MCPClientError(
                        f"SSE GET returned HTTP {resp.status_code}"
                    )
                event, datas = "message", []
                async for raw_line in resp.aiter_lines():
                    line = raw_line.rstrip("\r")
                    if line == "":
                        if datas:
                            self._dispatch(event, "\n".join(datas))
                        event, datas = "message", []
                    elif line.startswith("event:"):
                        event = line[6:].strip()
                    elif line.startswith("data:"):
                        datas.append(line[5:].lstrip())
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._reader_error = e
            self._endpoint_ready.set()  # unblock start()

    def _dispatch(self, event: str, data: str) -> None:
        if event == "endpoint":
            self._post_url = urljoin(self._url, data.strip())
            self._endpoint_ready.set()
        else:
            with contextlib.suppress(json.JSONDecodeError):
                self._inbox.put_nowait(json.loads(data))

    async def send(self, msg: Dict[str, Any]) -> None:
        if self._client is None or self._post_url is None:
            raise MCPClientError("SSE transport not started")
        try:
            resp = await self._client.post(self._post_url, json=msg)
        except Exception as e:
            raise MCPClientError(f"POST {self._post_url} failed: {e}")
        if resp.status_code >= 400:
            raise MCPClientError(
                f"MCP server returned HTTP {resp.status_code}"
            )

    async def recv(self) -> Dict[str, Any]:
        return await self._inbox.get()

    async def close(self) -> None:
        if self._reader_task is not None:
            self._reader_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._reader_task
            self._reader_task = None
        client, self._client = self._client, None
        if client is not None:
            with contextlib.suppress(Exception):
                await client.aclose()


def _iter_sse_datas(text: str):
    """Yield the data payload of each event in a buffered SSE body."""
    datas: List[str] = []
    for raw_line in text.splitlines() + [""]:
        line = raw_line.rstrip("\r")
        if line == "":
            if datas:
                yield "\n".join(datas)
            datas = []
        elif line.startswith("data:"):
            datas.append(line[5:].lstrip())


# ---------------------------------------------------------------------------
# Connection
# ---------------------------------------------------------------------------


@dataclass
class _Pending:
    future: asyncio.Future
    progress: Optional[asyncio.Queue] = None


class MCPConnection:
    """Lifecycle + JSON-RPC routing for one MCP server.

    connect(): start transport, `initialize` handshake, `notifications/
    initialized`, `tools/list` discovery. discovered_tools() returns `Tool`
    objects whose handlers stream through call_tool_stream().
    """

    def __init__(self, config: MCPServerConfig, timeout: float = 30.0):
        self.config = config
        self.timeout = timeout
        self.connected = False
        self.server_info: Dict[str, Any] = {}
        self._transport: Any = None
        self._tools: List[Tool] = []
        self._pending: Dict[Any, _Pending] = {}
        self._next_id = 0
        self._router_task: Optional[asyncio.Task] = None

    # -- lifecycle -----------------------------------------------------

    async def connect(self) -> None:
        cfg = self.config
        transport = cfg.effective_transport
        if transport == "stdio":
            if not cfg.command:
                raise MCPClientError(
                    f"MCP server {cfg.name}: stdio transport needs a command"
                )
            self._transport = _StdioTransport(cfg.command, cfg.args, cfg.env)
            await self._open_session()
        elif cfg.url:
            # streamable-HTTP first, SSE fallback (reference
            # src/tools/agent.py:113-162)
            try:
                self._transport = _StreamableHTTPTransport(
                    cfg.url, self.timeout
                )
                await self._open_session()
            except Exception as first_err:
                await self._teardown()
                logger.info(
                    "MCP %s: streamable-http failed (%s); trying SSE",
                    cfg.name, first_err,
                )
                self._transport = _SSETransport(cfg.url, self.timeout)
                try:
                    await self._open_session()
                except Exception:
                    await self._teardown()
                    raise
        else:
            raise MCPClientError(
                f"MCP server {cfg.name} must have either 'command' or 'url'"
            )
        self.connected = True

    async def _open_session(self) -> None:
        await self._transport.start()
        self._router_task = asyncio.create_task(self._route_inbound())
        try:
            init = await self._request(
                "initialize",
                {
                    "protocolVersion": PROTOCOL_VERSION,
                    "capabilities": {},
                    "clientInfo": CLIENT_INFO,
                },
            )
            self.server_info = init.get("serverInfo", {})
            await self._notify("notifications/initialized", {})
            await self._discover_tools()
        except Exception:
            await self._teardown()
            raise

    async def disconnect(self) -> None:
        self.connected = False
        await self._teardown()

    async def _teardown(self) -> None:
        if self._router_task is not None:
            self._router_task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._router_task
            self._router_task = None
        for pending in self._pending.values():
            if not pending.future.done():
                pending.future.set_exception(
                    MCPClientError("connection closed")
                )
        self._pending.clear()
        transport, self._transport = self._transport, None
        if transport is not None:
            with contextlib.suppress(Exception):
                await transport.close()

    # -- JSON-RPC plumbing ---------------------------------------------

    async def _route_inbound(self) -> None:
        try:
            while True:
                msg = await self._transport.recv()
                if not isinstance(msg, dict):
                    continue
                if "id" in msg and ("result" in msg or "error" in msg):
                    pending = self._pending.pop(msg["id"], None)
                    if pending is not None and not pending.future.done():
                        if "error" in msg:
                            err = msg["error"]
                            pending.future.set_exception(MCPClientError(
                                f"{err.get('message', err)} "
                                f"(code {err.get('code')})"
                            ))
                        else:
                            pending.future.set_result(msg.get("result"))
                elif msg.get("method") == "notifications/progress":
                    params = msg.get("params", {})
                    tok = params.get("progressToken")
                    for pending in self._pending.values():
                        if pending.progress is not None and (
                            tok is None or pending.progress_token == tok
                        ):
                            pending.progress.put_nowait(params)
                # other notifications (logging, list_changed) are ignored
        except asyncio.CancelledError:
            raise
        except Exception as e:
            for pending in self._pending.values():
                if not pending.future.done():
                    pending.future.set_exception(
                        MCPClientError(f"transport failed: {e}")
                    )
            self._pending.clear()

    async def _request(
        self,
        method: str,
        params: Dict[str, Any],
        progress: Optional[asyncio.Queue] = None,
        timeout: Optional[float] = None,
    ) -> Any:
        self._next_id += 1
        msg_id = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        pending = _Pending(future=fut, progress=progress)
        pending.progress_token = msg_id  # type: ignore[attr-defined]
        self._pending[msg_id] = pending
        req = {"jsonrpc": "2.0", "id": msg_id, "method": method,
               "params": params}
        if progress is not None:
            req["params"] = dict(params)
            req["params"].setdefault("_meta", {})["progressToken"] = msg_id
        try:
            await self._transport.send(req)
            return await asyncio.wait_for(fut, timeout or self.timeout)
        except asyncio.TimeoutError:
            raise MCPClientError(f"{method} timed out after "
                                 f"{timeout or self.timeout}s")
        finally:
            self._pending.pop(msg_id, None)

    async def _notify(self, method: str, params: Dict[str, Any]) -> None:
        await self._transport.send(
            {"jsonrpc": "2.0", "method": method, "params": params}
        )

    # -- tools ---------------------------------------------------------

    async def _discover_tools(self) -> None:
        result = await self._request("tools/list", {})
        self._tools = []
        for td in result.get("tools", []):
            name = td.get("name")
            if not name:
                continue
            self._tools.append(Tool(
                name=name,
                description=td.get("description") or "",
                parameters=td.get("inputSchema")
                or {"type": "object", "properties": {}},
                handler=None,  # dispatched via call_tool_stream
                source="mcp",
                metadata={"mcp_server": self.config.name},
            ))

    def discovered_tools(self) -> List[Tool]:
        """Tools with streaming handlers bound to this connection."""
        bound = []
        for t in self._tools:
            bound.append(Tool(
                name=t.name,
                description=t.description,
                parameters=t.parameters,
                handler=self._make_handler(t.name),
                source="mcp",
                metadata=dict(t.metadata),
            ))
        return bound

    def _make_handler(self, tool_name: str):
        async def handler(**arguments):
            async for ev in self.call_tool_stream(tool_name, arguments):
                yield ev

        handler.__name__ = f"mcp_{tool_name}"
        return handler

    async def call_tool_stream(
        self, name: str, arguments: Dict[str, Any],
        timeout: Optional[float] = None,
    ) -> AsyncIterator[ToolEvent]:
        """Invoke a tool; progress notifications stream as log events,
        the terminal result flattens MCP content blocks to text."""
        if self._transport is None:
            yield ToolEvent("error", "MCP connection closed", tool_name=name)
            return
        progress: asyncio.Queue = asyncio.Queue()
        call = asyncio.create_task(self._request(
            "tools/call", {"name": name, "arguments": arguments},
            progress=progress, timeout=timeout or max(self.timeout, 120.0),
        ))
        try:
            while not call.done():
                getter = asyncio.create_task(progress.get())
                done, _ = await asyncio.wait(
                    {call, getter}, return_when=asyncio.FIRST_COMPLETED
                )
                if getter in done:
                    params = getter.result()
                    msg = params.get("message") or (
                        f"progress {params.get('progress')}"
                        + (f"/{params['total']}" if params.get("total")
                           else "")
                    )
                    yield ToolEvent("log", msg, tool_name=name)
                else:
                    getter.cancel()
                    with contextlib.suppress(asyncio.CancelledError):
                        await getter
            result = call.result()
        except MCPClientError as e:
            yield ToolEvent("error", str(e), tool_name=name)
            return
        finally:
            if not call.done():
                call.cancel()
                with contextlib.suppress(Exception):
                    await call
        # drain any progress that raced the completion
        while not progress.empty():
            params = progress.get_nowait()
            if params.get("message"):
                yield ToolEvent("log", params["message"], tool_name=name)
        text = _flatten_content(result)
        if isinstance(result, dict) and result.get("isError"):
            yield ToolEvent("error", text or "tool reported an error",
                            tool_name=name)
        else:
            yield ToolEvent("result", text, tool_name=name)

    async def call_tool(self, name: str, arguments: Dict[str, Any]) -> str:
        last_err: Optional[str] = None
        async for ev in self.call_tool_stream(name, arguments):
            if ev.kind == "result":
                return ev.text()
            if ev.kind == "error":
                last_err = ev.text()
        raise MCPClientError(last_err or "tool call produced no result")


def _flatten_content(result: Any) -> str:
    """MCP tool results carry a list of content blocks; flatten to text."""
    if not isinstance(result, dict):
        return json.dumps(result) if result is not None else ""
    blocks = result.get("content")
    if blocks is None:
        sc = result.get("structuredContent")
        return json.dumps(sc) if sc is not None else json.dumps(result)
    parts: List[str] = []
    for block in blocks:
        if not isinstance(block, dict):
            parts.append(str(block))
        elif block.get("type") == "text":
            parts.append(block.get("text", ""))
        elif block.get("type") == "resource":
            res = block.get("resource", {})
            parts.append(res.get("text") or res.get("uri", ""))
        else:
            parts.append(json.dumps(block))
    return "\n".join(p for p in parts if p)
