"""Tool definitions and streaming tool events.

Parity targets: reference `Tool` (src/tools/types.py:39-219 — sync, async,
and async-generator handlers behind one `run`/`run_stream` interface) and
the sandbox `ToolEvent` streaming unit (src/sandbox/types.py:41-70).
`SandboxTool` lives in the sandbox tier (sandbox/tools.py) — this module is
dependency-free so the agent loop can import it without pulling IO code.
"""

from __future__ import annotations

import asyncio
import inspect
import json
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from ..core.toolcalls import parse_tool_arguments  # canonical impl (re-export)


@dataclass(slots=True)
class ToolEvent:
    """One streamed unit of tool output.

    kind: "delta" (incremental output), "log" (diagnostic), "result"
    (terminal value), "error" (terminal failure).
    """

    kind: str
    data: Any = None
    tool_name: Optional[str] = None
    tool_call_id: Optional[str] = None

    @property
    def terminal(self) -> bool:
        return self.kind in ("result", "error")

    def text(self) -> str:
        if isinstance(self.data, str):
            return self.data
        return json.dumps(self.data) if self.data is not None else ""


@dataclass
class Tool:
    """A callable tool exposed to the LLM.

    `handler(**arguments)` may be a plain function, an async function, or an
    async generator (streaming). All three are normalized to the streaming
    interface by `run_stream`; `run` collects the terminal result.
    """

    name: str
    description: str
    parameters: Dict[str, Any] = field(
        default_factory=lambda: {"type": "object", "properties": {}}
    )
    handler: Optional[Callable[..., Any]] = None
    source: str = "local"
    # extra metadata (e.g. which sandbox/MCP server owns it)
    metadata: Dict[str, Any] = field(default_factory=dict)

    def to_openai(self) -> Dict[str, Any]:
        return {
            "type": "function",
            "function": {
                "name": self.name,
                "description": self.description,
                "parameters": self.parameters,
            },
        }

    async def run_stream(
        self, arguments: Dict[str, Any]
    ) -> AsyncIterator[ToolEvent]:
        """Execute the handler, yielding events; always ends terminal."""
        if self.handler is None:
            yield ToolEvent("error", f"tool {self.name} has no handler",
                            tool_name=self.name)
            return
        try:
            if inspect.isasyncgenfunction(self.handler):
                parts: List[Any] = []
                async for item in self.handler(**arguments):
                    if isinstance(item, ToolEvent):
                        yield item
                        if item.terminal:
                            return
                        continue
                    parts.append(item)
                    yield ToolEvent("delta", item, tool_name=self.name)
                # terminal result aggregates the whole stream (the model must
                # see full output, not the last fragment): concatenate text
                # streams; otherwise the last value wins
                if parts and all(isinstance(p, str) for p in parts):
                    result: Any = "".join(parts)
                else:
                    result = parts[-1] if parts else None
                yield ToolEvent("result", result, tool_name=self.name)
            elif inspect.iscoroutinefunction(self.handler):
                result = await self.handler(**arguments)
                yield ToolEvent("result", result, tool_name=self.name)
            else:
                # sync handler: run off-loop so slow tools don't stall serving
                result = await asyncio.to_thread(self.handler, **arguments)
                yield ToolEvent("result", result, tool_name=self.name)
        except Exception as e:  # tool errors are data, not crashes
            yield ToolEvent("error", f"{type(e).__name__}: {e}",
                            tool_name=self.name)

    async def run(self, arguments: Dict[str, Any]) -> Any:
        """Non-streaming execution; returns the terminal result.

        Raises ToolExecutionError on a terminal error event.
        """
        last: Any = None
        async for ev in self.run_stream(arguments):
            if ev.kind == "result":
                return ev.data
            if ev.kind == "error":
                raise ToolExecutionError(str(ev.data), tool_name=self.name)
            last = ev.data
        return last


class ToolExecutionError(Exception):
    def __init__(self, message: str, tool_name: Optional[str] = None):
        super().__init__(message)
        self.tool_name = tool_name


@dataclass
class MCPServerConfig:
    """Connection config for an MCP tool server.

    Parity: reference src/tools/types.py:377 — stdio (command+args) or
    HTTP (url) transports.
    """

    name: str
    command: Optional[str] = None
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    url: Optional[str] = None
    transport: Optional[str] = None  # "stdio" | "streamable-http" | "sse"

    @property
    def effective_transport(self) -> str:
        if self.transport:
            return self.transport
        return "stdio" if self.command else "streamable-http"
