"""Tool tier: definitions, provider ABC, and source-routed execution."""

from .base import ToolProvider
from .provider import AgentToolProvider
from .types import (
    MCPServerConfig,
    Tool,
    ToolEvent,
    ToolExecutionError,
    parse_tool_arguments,
)

__all__ = [
    "AgentToolProvider",
    "MCPServerConfig",
    "Tool",
    "ToolEvent",
    "ToolExecutionError",
    "ToolProvider",
    "parse_tool_arguments",
]
