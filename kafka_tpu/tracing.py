"""End-to-end request tracing: a span tree per request, across processes.

PR 1 gave the stack real counters and PR 2 taught fault injection to cross
process boundaries; this module answers the question neither can: *where
did THIS request spend its time* once it fans out across the engine
thread, the agent tool loop, a sandbox subprocess, and a DP replica.

Design, mirroring the two disciplines this repo already trusts:

* **EngineMetrics' single-writer/torn-tolerant store.**  Traces live in a
  bounded in-memory ring (`_traces`, an OrderedDict capped at
  ``KAFKA_TPU_TRACE_RING`` entries).  Span recording is a plain
  ``list.append`` (GIL-atomic) onto the owning trace — no lock on any hot
  path; readers (`/debug/trace`, the slow-request log) take torn-tolerant
  snapshots (retry-on-RuntimeError, same policy as runtime/metrics.py).
* **failpoints' cross-process seam.**  The trace context serializes into
  the sandbox wire protocol (``POST /run`` carries ``{"trace": {...}}``)
  and the subprocess environment (:func:`subprocess_env`), so a
  ``tool.exec`` span's children are *recorded inside the sandbox process*
  (:class:`ChildSpans`), shipped back as a ``{"kind": "spans"}`` SSE frame,
  and stitched into the parent's trace by trace ID (:func:`stitch`).

**Hot-path contract** (acceptance-tested): an untraced request costs ONE
branch per would-be span (``ctx is None``); a traced request costs that
branch plus one ring append.  The sampling knob ``KAFKA_TPU_TRACE_SAMPLE``
(default 1.0 — sampling-*down* is the thing that's disabled by default)
decides per request at ingress; everything downstream keys off the
request's carried context, never off a global.

**Span registry.**  Like failpoints' SITES, every span name emitted in
code must appear in :data:`SPANS` (and every trace-level event name in
:data:`EVENTS`) — enforced both directions by a static check in
tests/test_tracing.py, so the trace schema cannot silently drift.

Timestamps are wall-clock (``time.time()``), the only base comparable
across PID boundaries; durations measured monotonically by callers are
converted at record time (``record_span(dur_s=...)``).

Export is Chrome trace-event JSON (``GET /debug/trace/{request_id}``),
loadable in Perfetto / chrome://tracing; ``GET /debug/traces`` serves a
recent-traces index.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import itertools
import json
import logging
import os
import random
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, NamedTuple, Optional

logger = logging.getLogger("kafka_tpu.tracing")

ENV_SAMPLE = "KAFKA_TPU_TRACE_SAMPLE"
ENV_RING = "KAFKA_TPU_TRACE_RING"
ENV_SPAN_CAP = "KAFKA_TPU_TRACE_SPAN_CAP"
ENV_SLOW_TTFT = "KAFKA_TPU_SLOW_TTFT_MS"
ENV_SLOW_TOTAL = "KAFKA_TPU_SLOW_TOTAL_MS"
ENV_PROFILING = "KAFKA_TPU_PROFILING"
# Span-ring persistence (PR 3 follow-up, closed by ISSUE 9): finished
# traces are also written as JSON files under this directory, so the ring
# survives process restarts alongside the disk KV tier.  Unset, it
# defaults to <KAFKA_TPU_KV_DISK_TIER_DIR>/traces when the disk tier is
# configured — the span ring persists "alongside the disk tier" with no
# extra knob.  Explicit "" disables persistence even with a disk tier.
ENV_PERSIST = "KAFKA_TPU_TRACE_PERSIST_DIR"
# the disk/object tier envs are read by name (kv_tier.py/object_tier.py
# own them; importing the runtime tier here would defeat this module's
# import-light contract).  With an OBJECT store configured the ring
# persists under it by preference — thread state that outlives the host
# should carry its trace history along (ISSUE 14).
_ENV_DISK_TIER = "KAFKA_TPU_KV_DISK_TIER_DIR"
_ENV_OBJECT_DIR = "KAFKA_TPU_KV_OBJECT_DIR"

# The DOCUMENTED SPAN REGISTRY: every span name emitted anywhere in
# kafka_tpu/ (tracing.span("..."), record_span(ctx, "..."),
# ChildSpans.span("..."), start_trace(name="...")) must appear here and
# vice versa — static check in tests/test_tracing.py, same contract as
# failpoints.SITES.
SPANS = (
    "http.request",   # root: HTTP ingress to response complete (server/app)
    "agent.turn",     # one LLM completion of the agent loop (agents/base)
    "tool.exec",      # one tool call, client side (tools/provider)
    "compaction",     # context-compaction retry (agents/base)
    "engine.queue",   # submit -> first prefill chunk dispatch (engine)
    "engine.prefill", # prefill chunks -> first token sampled (engine)
    "engine.decode",  # one decode dispatch burst; attrs: steps, busy — and
                      # on speculative verify dispatches proposed/accepted
                      # (candidate tokens offered / kept that round) (engine)
    "emit",           # first dispatch -> first token on host (engine)
    "sandbox.exec",   # tool execution INSIDE the sandbox subprocess
    "kv.demote",      # page run copied device->host under pressure; attrs:
                      # pages, bytes, overlap (runtime/kv_tier.py)
    "kv.promote",     # page run re-materialized host->device ahead of the
                      # suffix prefill; attrs: pages, bytes, source, overlap
    "kv.object_put",  # run archived into the shared object store; attrs:
                      # pages, bytes (runtime/object_tier.py)
    "kv.object_get",  # run fetched from the shared object store during a
                      # thread wake; attrs: pages, bytes, source
    "kv.prefetch",    # one run prefetched ahead of admission (wake
                      # prefetch, ISSUE 19); attrs: bytes, thread, hit
                      # (runtime/object_tier.WakePrefetcher)
    "thread.wake",    # dormant thread re-materialized from its sleep
                      # manifest; attrs: tokens, runs, bytes, source
                      # (runtime/prefix_cache.py)
)

# Trace-level instant events (supervisor actions that punctuate a request's
# timeline rather than span it).  Same both-directions static check.
EVENTS = (
    "preempt",         # engine rolled the request back to the queue
    "migrate",         # dp_router moved the queued request off a sick replica
    "quarantine",      # the request's replica was circuit-broken mid-flight
    "engine.recover",  # engine failure terminated the request
    "anomaly",         # a flight-recorder detector fired on the request's
                       # engine (attrs: kind, detail — flight_recorder.py)
    "resume",          # re-prefill admission after preemption or a
                       # disaggregated hand-off; attrs: tokens plus the
                       # radix share (cached_tokens / cache_source —
                       # "shipped" proves zero-re-prefill) (engine)
    "handoff",         # dp_router shipped the thread's prefilled pages to
                       # a decode replica; attrs: from_replica, to_replica,
                       # shipped_pages, shipped_bytes, shipped (bool)
)


class TraceContext(NamedTuple):
    """What crosses a boundary: enough to parent new spans."""

    trace_id: str
    span_id: str


@dataclasses.dataclass
class Span:
    """One recorded span.  `t1 is None` = still open (export flags it)."""

    name: str
    span_id: str
    parent_id: Optional[str]
    t0: float                       # wall-clock seconds
    t1: Optional[float] = None
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    thread: str = ""
    pid: int = 0

    def to_wire(self) -> Dict[str, Any]:
        return {
            "name": self.name, "span_id": self.span_id,
            "parent_id": self.parent_id, "t0": self.t0, "t1": self.t1,
            "attrs": self.attrs, "thread": self.thread, "pid": self.pid,
        }


@dataclasses.dataclass
class Trace:
    """One request's span tree + instant events."""

    trace_id: str
    request_id: str
    t0: float
    spans: List[Span] = dataclasses.field(default_factory=list)
    events: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    root_id: str = ""
    done: bool = False
    # spans refused by the per-trace cap (_span_cap): long generations
    # must not grow a trace without bound
    dropped_spans: int = 0
    _ids: Iterator[int] = dataclasses.field(
        default_factory=lambda: itertools.count(1)
    )

    def next_span_id(self) -> str:
        # per-trace counter: unique within the trace, no uuid on hot paths
        return f"{self.trace_id[:8]}.{next(self._ids)}"


# ---------------------------------------------------------------------------
# module state (the ring store + config)
# ---------------------------------------------------------------------------

_lock = threading.Lock()  # guards ring insertion/eviction only (cold path)
_traces: "OrderedDict[str, Trace]" = OrderedDict()
_by_request: Dict[str, str] = {}  # request_id -> trace_id alias

_sample = 1.0
_capacity = 256
# Per-trace span bound: a 16k-token generation records thousands of
# engine.decode bursts; past the cap further spans drop (counted in the
# trace's dropped_spans) so a long stream cannot grow memory unboundedly.
_span_cap = 2048
_slow_ttft_ms: Optional[float] = None
_slow_total_ms: Optional[float] = None
_profiling = False
_persist_dir: Optional[str] = None
_counters: Dict[str, int] = {
    "slow": 0, "traces": 0, "stitched_spans": 0, "persisted": 0,
}

_ctx: "contextvars.ContextVar[Optional[TraceContext]]" = (
    contextvars.ContextVar("kafka_tpu_trace_ctx", default=None)
)


def configure(
    sample: Optional[float] = None,
    ring: Optional[int] = None,
    slow_ttft_ms: Optional[float] = None,
    slow_total_ms: Optional[float] = None,
    profiling: Optional[bool] = None,
    span_cap: Optional[int] = None,
    persist_dir: Optional[str] = None,
) -> None:
    """Programmatic config (server boot / tests).  None = leave as is;
    for the slow thresholds, 0 disables (matching the env contract); for
    persist_dir, "" disables persistence."""
    global _sample, _capacity, _slow_ttft_ms, _slow_total_ms, _profiling
    global _span_cap, _persist_dir
    if persist_dir is not None:
        _persist_dir = persist_dir or None
        if _persist_dir:
            try:
                os.makedirs(_persist_dir, exist_ok=True)
            except OSError as e:
                logger.warning(
                    "trace persistence disabled (cannot create %s: %s)",
                    _persist_dir, e,
                )
                _persist_dir = None
    if sample is not None:
        _sample = max(0.0, min(1.0, float(sample)))
    if ring is not None:
        _capacity = max(1, int(ring))
    if span_cap is not None:
        _span_cap = max(1, int(span_cap))
    if slow_ttft_ms is not None:
        _slow_ttft_ms = float(slow_ttft_ms) or None
    if slow_total_ms is not None:
        _slow_total_ms = float(slow_total_ms) or None
    if profiling is not None:
        _profiling = bool(profiling)


def load_env() -> None:
    """Read the env knobs (import time + server startup, like failpoints)."""
    env = os.environ
    if ENV_PERSIST in env:
        persist = env[ENV_PERSIST]  # explicit, "" = off
    elif env.get(_ENV_OBJECT_DIR):
        # persist the ring alongside the OBJECT KV tier by preference:
        # portable thread state carries its trace history across hosts
        persist = os.path.join(env[_ENV_OBJECT_DIR], "traces")
    elif env.get(_ENV_DISK_TIER):
        # persist the ring alongside the disk KV tier by default
        persist = os.path.join(env[_ENV_DISK_TIER], "traces")
    else:
        persist = ""
    configure(
        sample=float(env.get(ENV_SAMPLE, "1.0")),
        ring=int(env.get(ENV_RING, "256")),
        span_cap=int(env.get(ENV_SPAN_CAP, "2048")),
        slow_ttft_ms=float(env.get(ENV_SLOW_TTFT, "0") or 0),
        slow_total_ms=float(env.get(ENV_SLOW_TOTAL, "0") or 0),
        profiling=env.get(ENV_PROFILING, "0") in ("1", "true"),
        persist_dir=persist,
    )


def sample_rate() -> float:
    return _sample


def profiler_annotations_enabled() -> bool:
    """Should the engine wrap device dispatches in jax.profiler named
    scopes keyed by trace id?  Costs one module-global bool read."""
    return _profiling


def reset() -> None:
    """Test hygiene: clear the store and counters, reload env config."""
    with _lock:
        _traces.clear()
        _by_request.clear()
    for k in _counters:
        _counters[k] = 0
    load_env()


def counters() -> Dict[str, int]:
    return dict(_counters)


def persist_dir() -> Optional[str]:
    """The configured trace-persistence directory (None = persistence
    off).  The flight recorder's postmortem dumps land alongside the
    persisted trace rings by default (runtime/flight_recorder.py)."""
    return _persist_dir


def slow_count() -> int:
    return _counters["slow"]


def subprocess_env(base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for a child process inheriting the tracing/log config
    (sandbox subprocesses — the same seam failpoints.subprocess_env uses).
    The live values are serialized, not just whatever the parent's env
    happens to hold: programmatic configure() must reach children too."""
    env = dict(os.environ if base is None else base)
    env[ENV_SAMPLE] = repr(_sample)
    if _profiling:
        env[ENV_PROFILING] = "1"
    # KAFKA_TPU_LOG_FORMAT rides along untouched (env-only knob): children
    # of a json-logging parent log json (logs.setup_logging reads it)
    return env


# ---------------------------------------------------------------------------
# trace lifecycle
# ---------------------------------------------------------------------------


def _register(trace: Trace) -> None:
    with _lock:
        _traces[trace.trace_id] = trace
        _by_request[trace.request_id] = trace.trace_id
        while len(_traces) > _capacity:
            _, evicted = _traces.popitem(last=False)
            _by_request.pop(evicted.request_id, None)
    _counters["traces"] += 1


def new_trace_id() -> str:
    return uuid.uuid4().hex


def start_trace(
    request_id: Optional[str] = None,
    trace_id: Optional[str] = None,
    parent_id: Optional[str] = None,
    name: str = "http.request",
    attrs: Optional[Dict[str, Any]] = None,
) -> Optional[Span]:
    """Mint (or adopt) a trace and open its root span; sets the context.

    Returns None when the request is sampled out (``KAFKA_TPU_TRACE_SAMPLE``
    < 1) — an adopted trace id (incoming ``X-Request-Id``/``traceparent``)
    bypasses probabilistic sampling (the caller asked for this request by
    name), but NOT the hard off switch: at sample 0 nothing is traced, so
    a proxy that stamps X-Request-Id on every request cannot re-enable
    tracing a deployment turned off.
    """
    if _sample <= 0.0:
        return None
    if trace_id is None:
        if _sample < 1.0 and random.random() >= _sample:
            return None
        trace_id = new_trace_id()
    trace = Trace(
        trace_id=trace_id,
        request_id=request_id or trace_id,
        t0=time.time(),
    )
    root = Span(
        name=name,
        span_id=trace.next_span_id(),
        parent_id=parent_id,
        t0=trace.t0,
        attrs=dict(attrs or {}),
        thread=threading.current_thread().name,
        pid=os.getpid(),
    )
    trace.root_id = root.span_id
    trace.spans.append(root)
    _register(trace)
    _ctx.set(TraceContext(trace_id, root.span_id))
    return root


def finish_trace(root: Optional[Span], status: Any = None) -> None:
    """Close the root span, mark the trace done, and run the slow-request
    check (one structured log line + the ``requests.slow`` counter when a
    configured TTFT/total threshold is exceeded)."""
    if root is None:
        return
    root.t1 = time.time()
    if status is not None:
        root.attrs["status"] = status
    ctx = _ctx.get()
    trace = _traces.get(ctx.trace_id) if ctx is not None else None
    if trace is None or trace.root_id != root.span_id:
        # context already gone (or belongs to a nested span): resolve by
        # scanning the small ring — cold path, once per request
        trace = next(
            (tr for tr in list(_traces.values())
             if tr.root_id == root.span_id and root in tr.spans),
            None,
        )
    if trace is None:
        return  # evicted under pressure, or finish after reset()
    if ctx is not None:
        _ctx.set(None)
    trace.done = True
    if _persist_dir is not None:
        _persist(trace)
    _check_slow(trace, root)


def _check_slow(trace: Trace, root: Span) -> None:
    total_ms = (root.t1 - root.t0) * 1e3
    ttft_ms: Optional[float] = None
    for s in list(trace.spans):
        # the engine's `emit` span ends when the first token reaches the
        # host — its end relative to ingress is the request's true TTFT
        if s.name == "emit" and s.t1 is not None:
            t = (s.t1 - root.t0) * 1e3
            ttft_ms = t if ttft_ms is None else min(ttft_ms, t)
    slow = (
        _slow_total_ms is not None and total_ms > _slow_total_ms
    ) or (
        _slow_ttft_ms is not None
        and ttft_ms is not None
        and ttft_ms > _slow_ttft_ms
    )
    if not slow:
        return
    _counters["slow"] += 1
    logger.warning(
        "slow request %s: total=%.1fms ttft=%s slo_met=%s (thresholds: "
        "ttft=%s total=%s)",
        trace.request_id, total_ms,
        f"{ttft_ms:.1f}ms" if ttft_ms is not None else "n/a",
        # the engine's SLO verdict (annotate() stamped it on the root at
        # finalize; ISSUE 10) — a slow-log line is actionable only if it
        # says whether the request also MISSED its SLO or merely tripped
        # the softer slow threshold
        root.attrs.get("slo_met"),
        _slow_ttft_ms, _slow_total_ms,
        extra={
            "trace_id": trace.trace_id,
            "span_id": root.span_id,
            "slow_request": True,
            "total_ms": round(total_ms, 1),
            "ttft_ms": round(ttft_ms, 1) if ttft_ms is not None else None,
            "slo_met": root.attrs.get("slo_met"),
            "spans": span_breakdown(trace),
        },
    )


def span_breakdown(trace: Trace) -> List[Dict[str, Any]]:
    """The full span timeline as plain dicts (slow-request log payload)."""
    out = []
    for s in list(trace.spans):
        out.append({
            "name": s.name,
            "start_ms": round((s.t0 - trace.t0) * 1e3, 2),
            "dur_ms": round(((s.t1 or time.time()) - s.t0) * 1e3, 2),
            "span_id": s.span_id,
            "parent_id": s.parent_id,
            **({"attrs": s.attrs} if s.attrs else {}),
        })
    return out


# ---------------------------------------------------------------------------
# in-context spans (asyncio serving path)
# ---------------------------------------------------------------------------


def _has_room(trace: Trace) -> bool:
    """Per-trace span cap: refuse (and count) appends past _span_cap."""
    if len(trace.spans) >= _span_cap:
        trace.dropped_spans += 1
        return False
    return True


def current() -> Optional[TraceContext]:
    """The ambient trace context (None = this request is untraced)."""
    return _ctx.get()


@contextlib.contextmanager
def span(name: str, attrs: Optional[Dict[str, Any]] = None):
    """Open a child span of the ambient context for the with-block.

    No-op (yields None) when untraced.  Nesting works through contextvars,
    so spans opened inside the block parent correctly.
    """
    ctx = _ctx.get()
    if ctx is None:
        yield None
        return
    trace = _traces.get(ctx.trace_id)
    if trace is None or not _has_room(trace):
        yield None
        return
    s = Span(
        name=name,
        span_id=trace.next_span_id(),
        parent_id=ctx.span_id,
        t0=time.time(),
        attrs=dict(attrs or {}),
        thread=threading.current_thread().name,
        pid=os.getpid(),
    )
    trace.spans.append(s)
    token = _ctx.set(TraceContext(ctx.trace_id, s.span_id))
    try:
        yield s
    finally:
        s.t1 = time.time()
        _ctx.reset(token)


# ---------------------------------------------------------------------------
# engine hot path (explicit-context, single branch + append)
# ---------------------------------------------------------------------------


def record_span(
    ctx: Optional[TraceContext],
    name: str,
    dur_s: float,
    attrs: Optional[Dict[str, Any]] = None,
    end: Optional[float] = None,
) -> None:
    """Append one CLOSED span to `ctx`'s trace.  The engine thread's API:
    callers measure duration monotonically and record at completion, so
    the only cost on the scheduler thread is this call — a None check for
    untraced requests, one list append for traced ones."""
    if ctx is None:
        return
    trace = _traces.get(ctx.trace_id)
    if trace is None or not _has_room(trace):
        return  # evicted mid-request, or span cap reached: drop (counted)
    t1 = end if end is not None else time.time()
    trace.spans.append(Span(
        name=name,
        span_id=trace.next_span_id(),
        parent_id=ctx.span_id,
        t0=t1 - max(0.0, dur_s),
        t1=t1,
        attrs=attrs or {},
        thread=threading.current_thread().name,
        pid=os.getpid(),
    ))


def annotate(
    ctx: Optional[TraceContext],
    attrs: Dict[str, Any],
) -> None:
    """Merge attrs onto the trace's ROOT span (http.request).

    The engine stamps each request's SLO verdict here at finalize
    (ISSUE 10): slo_met / ttft_ms / tpot_ms show on the request's root
    span in /debug/trace and ride the slow-request log's breakdown.
    Same cost contract as record_span — None check untraced, one dict
    update traced.  Races with finish_trace are benign (dict update)."""
    if ctx is None:
        return
    trace = _traces.get(ctx.trace_id)
    if trace is None or trace.root_id is None:
        return
    for s in list(trace.spans):
        if s.span_id == trace.root_id:
            s.attrs.update(attrs)
            return


def add_event(
    ctx: Optional[TraceContext],
    name: str,
    attrs: Optional[Dict[str, Any]] = None,
) -> None:
    """Append one instant event (supervisor actions: preempt/migrate/
    quarantine/...) to `ctx`'s trace.  Same cost contract as record_span."""
    if ctx is None:
        return
    trace = _traces.get(ctx.trace_id)
    if trace is None:
        return
    trace.events.append({
        "name": name,
        "t": time.time(),
        "attrs": attrs or {},
        "span_id": ctx.span_id,
    })


# ---------------------------------------------------------------------------
# cross-process: child-side collection + parent-side stitching
# ---------------------------------------------------------------------------


def wire_context() -> Optional[Dict[str, str]]:
    """The ambient context as a wire dict for the sandbox /run payload."""
    ctx = _ctx.get()
    if ctx is None:
        return None
    return {"trace_id": ctx.trace_id, "parent_span_id": ctx.span_id}


class ChildSpans:
    """Span collector for a process that does NOT own the trace store
    (the sandbox subprocess).  Spans are recorded locally and exported as
    wire dicts; the parent stitches them by trace ID (:func:`stitch`).
    Single-task usage per collector (one /run call each)."""

    def __init__(self, trace_id: str, parent_span_id: Optional[str]):
        self.trace_id = trace_id
        self.spans: List[Span] = []
        self._stack: List[Optional[str]] = [parent_span_id]
        self._ids = itertools.count(1)

    @contextlib.contextmanager
    def span(self, name: str, attrs: Optional[Dict[str, Any]] = None):
        s = Span(
            name=name,
            span_id=f"{self.trace_id[:8]}.c{os.getpid()}.{next(self._ids)}",
            parent_id=self._stack[-1],
            t0=time.time(),
            attrs=dict(attrs or {}),
            thread=threading.current_thread().name,
            pid=os.getpid(),
        )
        self.spans.append(s)
        self._stack.append(s.span_id)
        try:
            yield s
        finally:
            s.t1 = time.time()
            self._stack.pop()

    def export(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "spans": [s.to_wire() for s in self.spans],
        }


def child_collector(wire: Optional[Dict[str, Any]]) -> Optional[ChildSpans]:
    """Build a collector from a /run payload's ``trace`` field (or None
    when the request is untraced — the child then records nothing)."""
    if not wire or not wire.get("trace_id"):
        return None
    return ChildSpans(str(wire["trace_id"]), wire.get("parent_span_id"))


def stitch(payload: Dict[str, Any]) -> int:
    """Merge a child process's exported spans into the parent's trace
    (matched by trace ID).  Returns how many spans landed; spans for a
    trace the ring no longer holds are dropped (torn-tolerant, like every
    other read path)."""
    trace = _traces.get(str(payload.get("trace_id", "")))
    if trace is None:
        return 0
    n = 0
    for w in payload.get("spans", []):
        if not _has_room(trace):
            break
        try:
            trace.spans.append(Span(
                name=str(w["name"]),
                span_id=str(w["span_id"]),
                parent_id=w.get("parent_id"),
                t0=float(w["t0"]),
                t1=float(w["t1"]) if w.get("t1") is not None else None,
                attrs=dict(w.get("attrs") or {}),
                thread=str(w.get("thread", "")),
                pid=int(w.get("pid", 0)),
            ))
            n += 1
        except (KeyError, TypeError, ValueError):
            logger.warning("dropping malformed stitched span: %r", w)
    _counters["stitched_spans"] += n
    return n


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------


def get_trace(id_or_request_id: str) -> Optional[Trace]:
    trace = _traces.get(id_or_request_id)
    if trace is None:
        tid = _by_request.get(id_or_request_id)
        trace = _traces.get(tid) if tid else None
    if trace is None and _persist_dir is not None:
        trace = _load_persisted(id_or_request_id)
    return trace


# ---------------------------------------------------------------------------
# ring persistence (alongside the disk KV tier — PR 3 follow-up)
# ---------------------------------------------------------------------------

# files kept on disk: a few rings' worth, pruned oldest-first at write time
_PERSIST_KEEP_FACTOR = 4
# prune cadence: listdir + stat + sort over the whole directory is ~1k
# syscalls once full — amortize it instead of paying it per finished trace
_PRUNE_EVERY = 64


def sanitize_stem(raw: str) -> str:
    """Filesystem-safe file-name stem: a sanitized prefix for human
    ls-ability plus a digest of the full string for uniqueness.  THE
    path-traversal defense for every artifact named from untrusted
    content — persisted traces (ids adopted verbatim from X-Request-Id)
    and flight-recorder postmortems both derive names through this one
    helper, so a hardening change cannot drift between them."""
    import hashlib

    safe = "".join(
        c if c.isalnum() or c in "._-" else "_" for c in raw[:48]
    )
    digest = hashlib.sha1(raw.encode()).hexdigest()[:12]
    return f"{safe}.{digest}"


def _persist_name(trace_id: str) -> str:
    """Persisted-trace file name (see sanitize_stem: trace ids can be
    ADOPTED VERBATIM from a client's X-Request-Id header, so the id must
    never be used as a path — '../..' would write, and let /debug/trace
    read, outside the persist dir).  Computed identically on write and
    lookup."""
    return f"{sanitize_stem(trace_id)}.trace.json"


def _persist(trace: Trace) -> None:
    """Write one finished trace as JSON (best-effort, never raises into
    the serving path).  Files are named by a sanitized trace id; the
    request id lives in the payload for the fallback scan."""
    assert _persist_dir is not None
    payload = {
        "trace_id": trace.trace_id,
        "request_id": trace.request_id,
        "t0": trace.t0,
        "root_id": trace.root_id,
        "done": trace.done,
        "dropped_spans": trace.dropped_spans,
        "spans": [s.to_wire() for s in list(trace.spans)],
        "events": list(trace.events),
    }
    path = os.path.join(_persist_dir, _persist_name(trace.trace_id))
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        _counters["persisted"] += 1
        if _counters["persisted"] % _PRUNE_EVERY == 0:
            _prune_persisted()
    except OSError as e:
        logger.warning("trace persistence failed for %s: %s",
                       trace.trace_id, e)


def _prune_persisted() -> None:
    """Bound the persisted set to a few rings' worth (oldest dropped)."""
    assert _persist_dir is not None
    try:
        names = [n for n in os.listdir(_persist_dir)
                 if n.endswith(".trace.json")]
        keep = _capacity * _PERSIST_KEEP_FACTOR
        if len(names) <= keep:
            return
        paths = [os.path.join(_persist_dir, n) for n in names]
        paths.sort(key=lambda p: os.path.getmtime(p))
        for p in paths[: len(paths) - keep]:
            os.unlink(p)
    except OSError:
        pass


def _trace_from_payload(payload: Dict[str, Any]) -> Trace:
    trace = Trace(
        trace_id=str(payload["trace_id"]),
        request_id=str(payload.get("request_id", payload["trace_id"])),
        t0=float(payload.get("t0", 0.0)),
    )
    trace.root_id = str(payload.get("root_id", ""))
    trace.done = bool(payload.get("done", True))
    trace.dropped_spans = int(payload.get("dropped_spans", 0))
    for w in payload.get("spans", []):
        trace.spans.append(Span(
            name=str(w["name"]),
            span_id=str(w["span_id"]),
            parent_id=w.get("parent_id"),
            t0=float(w["t0"]),
            t1=float(w["t1"]) if w.get("t1") is not None else None,
            attrs=dict(w.get("attrs") or {}),
            thread=str(w.get("thread", "")),
            pid=int(w.get("pid", 0)),
        ))
    trace.events = list(payload.get("events", []))
    return trace


def _load_persisted(id_or_request_id: str) -> Optional[Trace]:
    """Disk fallback for a trace the ring evicted (or a prior process
    recorded).  Direct hit by the sanitized trace-id file name (the same
    derivation _persist used, so a hostile id cannot traverse out of the
    dir); otherwise a bounded newest-first scan matching request_id —
    cold path, debug endpoint."""
    assert _persist_dir is not None
    direct = os.path.join(_persist_dir, _persist_name(id_or_request_id))
    try:
        if os.path.exists(direct):
            with open(direct) as f:
                return _trace_from_payload(json.load(f))
        names = [n for n in os.listdir(_persist_dir)
                 if n.endswith(".trace.json")]
        paths = [os.path.join(_persist_dir, n) for n in names]
        paths.sort(key=lambda p: os.path.getmtime(p), reverse=True)
        for p in paths[:512]:
            try:
                with open(p) as f:
                    payload = json.load(f)
            except (OSError, ValueError):
                continue
            if payload.get("request_id") == id_or_request_id:
                return _trace_from_payload(payload)
    except OSError:
        return None
    return None


def recent_traces() -> List[Dict[str, Any]]:
    """Index of the ring, newest first (GET /debug/traces)."""
    with _lock:
        items = list(_traces.values())
    out = []
    for tr in reversed(items):
        spans = list(tr.spans)
        root = next((s for s in spans if s.span_id == tr.root_id), None)
        end = root.t1 if root is not None and root.t1 is not None else None
        out.append({
            "trace_id": tr.trace_id,
            "request_id": tr.request_id,
            "start": tr.t0,
            "duration_ms": round((end - tr.t0) * 1e3, 2) if end else None,
            "spans": len(spans),
            "dropped_spans": tr.dropped_spans,
            "events": len(tr.events),
            "done": tr.done,
            "names": sorted({s.name for s in spans}),
        })
    return out


def chrome_trace(id_or_request_id: str) -> Optional[Dict[str, Any]]:
    """Chrome trace-event JSON for one trace (Perfetto-loadable).

    Spans render as complete ("X") events; trace-level events as instants
    ("i").  Lanes: pid = recording process, tid = a stable small int per
    (pid, thread) pair, named via metadata ("M") records so Perfetto shows
    'engine'/'aiohttp'/'sandbox' rows instead of raw ids.
    """
    trace = get_trace(id_or_request_id)
    if trace is None:
        return None
    spans = list(trace.spans)  # torn-tolerant snapshot
    events: List[Dict[str, Any]] = []
    lanes: Dict[tuple, int] = {}
    own_pid = os.getpid()

    def lane(pid: int, thread: str) -> int:
        key = (pid, thread)
        if key not in lanes:
            lanes[key] = len(lanes) + 1
        return lanes[key]

    now = time.time()
    for s in spans:
        pid = s.pid or own_pid
        t1 = s.t1 if s.t1 is not None else now
        args = {"span_id": s.span_id, "parent_id": s.parent_id, **s.attrs}
        if s.t1 is None:
            args["unfinished"] = True
        events.append({
            "ph": "X",
            "name": s.name,
            "cat": "kafka_tpu",
            "ts": round(s.t0 * 1e6, 1),
            "dur": round(max(0.0, t1 - s.t0) * 1e6, 1),
            "pid": pid,
            "tid": lane(pid, s.thread),
            "args": args,
        })
    for ev in list(trace.events):
        events.append({
            "ph": "i",
            "name": ev["name"],
            "cat": "kafka_tpu",
            "ts": round(ev["t"] * 1e6, 1),
            "pid": own_pid,
            "tid": 0,
            "s": "p",
            "args": ev.get("attrs", {}),
        })
    for (pid, thread), tid in lanes.items():
        events.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": thread or f"pid-{pid}"},
        })
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": "kafka_tpu" if pid == own_pid
                     else f"sandbox-{pid}"},
        })
    return {
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": trace.trace_id,
            "request_id": trace.request_id,
            "done": trace.done,
        },
        "traceEvents": events,
    }


load_env()
