"""Version-compatibility shims for the pinned jax.

`shard_map` moved from `jax.experimental.shard_map` (kwarg `check_rep`)
to the public `jax.shard_map` (kwarg `check_vma`).  Call sites use the
public spelling; this shim maps it onto whichever API the installed jax
provides so the per-shard kernel dispatch works on both.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6: public API
    from jax import shard_map as _shard_map

    _public = True
except ImportError:  # jax 0.4.x: experimental module
    from jax.experimental.shard_map import shard_map as _shard_map

    _public = False

# The check_rep -> check_vma kwarg rename did NOT land together with the
# public re-export (public-but-check_rep versions exist in the 0.5/0.6
# transition band), so pick the kwarg from the actual signature; the
# import location is only the fallback when introspection fails.
try:
    _params = inspect.signature(_shard_map).parameters
    _CHECK_KWARG = ("check_vma" if "check_vma" in _params
                    else "check_rep" if "check_rep" in _params
                    else ("check_vma" if _public else "check_rep"))
except (TypeError, ValueError):
    _CHECK_KWARG = "check_vma" if _public else "check_rep"


try:  # jax >= 0.9: explicit varying-mesh-axes casts inside shard_map
    from jax.lax import pcast
except ImportError:  # jax 0.4.x has no vma tracking: pcast is a no-op

    def pcast(x, axes=None, *, to=None):
        return x


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KWARG: check_vma},
    )
