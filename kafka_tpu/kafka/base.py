"""KafkaAgent — the orchestrator contract + thread-history replay.

Parity: reference src/kafka/base.py:24-319.  `run` executes the agent loop
statelessly; `run_with_thread` adds durable thread semantics: fetch
history, sanitize, persist the new inbound messages, stream the run while
re-accumulating every streamed delta/tool-call into `Message`s, and persist
those at the end (:171-310).  The thread store is the recovery log — a
crashed server replays the thread and the TPU engine re-prefills its KV
cache from it (SURVEY §5.4).
"""

from __future__ import annotations

import abc
import logging
from typing import Any, AsyncIterator, Dict, List, Optional

from ..core.sanitize import sanitize_messages_for_openai
from ..core.types import Message
from ..db.base import DBClient
from .utils import MessageAccumulator

logger = logging.getLogger("kafka_tpu.kafka")


class KafkaAgent(abc.ABC):
    """Orchestrator ABC: initialize/cleanup/get_tools/run/run_with_thread."""

    #: thread store used by run_with_thread (set by the implementation)
    thread_db: Optional[DBClient] = None

    @abc.abstractmethod
    async def initialize(self) -> None:
        """Wire providers (LLM, tools, prompts, compaction). Idempotent."""

    async def cleanup(self) -> None:
        """Release connections. Idempotent."""

    @abc.abstractmethod
    def get_tools(self) -> List[Dict[str, Any]]:
        """Available tools in OpenAI format."""

    @abc.abstractmethod
    def run(
        self,
        messages: List[Any],
        model: Optional[str] = None,
        temperature: float = 0.7,
        max_tokens: Optional[int] = None,
        **kwargs: Any,
    ) -> AsyncIterator[Dict[str, Any]]:
        """Stateless agent run over `messages`; yields the event protocol
        (OpenAI chunks / tool_result / agent_done — agents/base.py)."""

    async def run_with_thread(
        self,
        thread_id: str,
        new_messages: List[Any],
        model: Optional[str] = None,
        temperature: float = 0.7,
        max_tokens: Optional[int] = None,
        **kwargs: Any,
    ) -> AsyncIterator[Dict[str, Any]]:
        """Run with durable thread history (reference base.py:171-310).

        History and the new inbound messages are persisted before the run
        starts; assistant/tool messages produced by the run are persisted
        after it completes (accumulated live from the stream).
        """
        if self.thread_db is None:
            raise RuntimeError("run_with_thread requires a thread store")
        db = self.thread_db
        await db.create_thread(thread_id)  # no-op if it exists
        history = [
            Message.from_dict(m) for m in await db.get_thread_messages(thread_id)
        ]
        new_msgs = [
            m if isinstance(m, Message) else Message.from_dict(dict(m))
            for m in new_messages
        ]
        await db.add_messages(thread_id, [m.to_dict() for m in new_msgs])
        working = sanitize_messages_for_openai(history + new_msgs)

        acc = MessageAccumulator()
        try:
            async for event in self.run(
                [m.to_dict() for m in working],
                model=model,
                temperature=temperature,
                max_tokens=max_tokens,
                **kwargs,
            ):
                acc.add_event(event)
                yield event
        finally:
            # persist whatever the run produced, even on mid-run failure —
            # a resumed thread must see the partial turn (tool results that
            # DID execute) rather than silently losing it
            to_save = [m.to_dict() for m in acc.messages]
            if to_save:
                await db.add_messages(thread_id, to_save)

    async def __aenter__(self) -> "KafkaAgent":
        await self.initialize()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.cleanup()
