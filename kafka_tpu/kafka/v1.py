"""KafkaV1Provider — concrete wiring of the whole stack.

Parity: reference src/kafka/v1.py:24-357, with the central substitution:
the LLM provider is the in-process TPU engine (llm/tpu_provider.py), not a
remote gateway.  The engine is an expensive shared singleton, so unlike the
reference (which built a fresh Portkey client per thread, v1.py:177-181)
this provider RECEIVES the LLMProvider and shares it across threads; what
is per-thread is the prompt (global_prompt + playbooks from the thread
config, v1.py:196-225), the tool set, and the agent instance.
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence

from ..agents import Agent
from ..db.base import DBClient
from ..llm.base import LLMProvider
from ..llm.compaction import (
    ContextCompactionProvider,
    SummarizationCompactionProvider,
)
from ..prompts import PromptProviderV1
from ..tools import AgentToolProvider, MCPServerConfig, Tool
from .base import KafkaAgent
from .utils import playbooks_to_markdown

logger = logging.getLogger("kafka_tpu.kafka.v1")


class KafkaV1Provider(KafkaAgent):
    def __init__(
        self,
        llm_provider: LLMProvider,
        thread_db: Optional[DBClient] = None,
        tools: Optional[Sequence[Tool]] = None,
        mcp_servers: Optional[Sequence[MCPServerConfig]] = None,
        thread_id: Optional[str] = None,
        system_prompt: Optional[str] = None,
        default_model: Optional[str] = None,
        compaction_provider: Optional[ContextCompactionProvider] = None,
        max_iterations: int = 50,
        parallel_tools: bool = False,
        prompt_variables: Optional[Dict[str, Any]] = None,
    ):
        self.llm = llm_provider
        self.thread_db = thread_db
        self._tools = list(tools or [])
        self._mcp_servers = list(mcp_servers or [])
        self.thread_id = thread_id
        self.system_prompt = system_prompt
        self.default_model = default_model
        self._compaction = compaction_provider
        self.max_iterations = max_iterations
        self.parallel_tools = parallel_tools
        self._prompt_variables = dict(prompt_variables or {})
        self.tool_provider: Optional[AgentToolProvider] = None
        self.prompt_provider: Optional[PromptProviderV1] = None
        self.agent: Optional[Agent] = None
        self._initialized = False

    # ------------------------------------------------------------------

    async def initialize(self) -> None:
        if self._initialized:
            return

        # per-thread config (model override, global_prompt, playbooks) —
        # reference v1.py:135-158
        thread_config: Dict[str, Any] = {}
        if self.thread_id and self.thread_db is not None:
            try:
                thread_config = (
                    await self.thread_db.get_thread_config(self.thread_id)
                ) or {}
            except Exception as e:
                logger.warning("thread config fetch failed: %s", e)
        # per-thread model override beats the request/server default: it is
        # the operator's explicit per-thread routing decision (the analog of
        # the reference's per-thread virtual-key routing, v1.py:135-158)
        if thread_config.get("model"):
            self.default_model = thread_config["model"]

        self.tool_provider = AgentToolProvider(
            tools=self._tools, mcp_servers=self._mcp_servers
        )
        await self.tool_provider.connect()

        if self._compaction is None:
            self._compaction = SummarizationCompactionProvider(
                self.llm, model=self.default_model
            )

        # prompt provider + dynamic sections (reference v1.py:196-225)
        if self.system_prompt is None:
            self.prompt_provider = PromptProviderV1(
                variables=self._prompt_variables
            )
            global_prompt = thread_config.get("global_prompt")
            if global_prompt:
                self.prompt_provider.add_section("global_prompt", global_prompt)
            playbooks = thread_config.get("playbooks") or []
            table = playbooks_to_markdown(playbooks)
            if table:
                self.prompt_provider.add_section("playbooks", table)

        self.agent = Agent(
            llm_provider=self.llm,
            tool_provider=self.tool_provider,
            system_prompt=self.system_prompt,
            prompt_provider=self.prompt_provider,
            context_compaction_provider=self._compaction,
            max_iterations=self.max_iterations,
            parallel_tools=self.parallel_tools,
        )
        self._initialized = True

    async def cleanup(self) -> None:
        if self.tool_provider is not None:
            await self.tool_provider.disconnect()
        self._initialized = False

    # ------------------------------------------------------------------

    def get_tools(self) -> List[Dict[str, Any]]:
        return self.tool_provider.get_tools() if self.tool_provider else []

    def register_tool(self, tool: Tool) -> None:
        if self.tool_provider is None:
            self._tools.append(tool)
        else:
            self.tool_provider.register_tool(tool)

    async def run(
        self,
        messages: List[Any],
        model: Optional[str] = None,
        temperature: float = 0.7,
        max_tokens: Optional[int] = None,
        **kwargs: Any,
    ) -> AsyncIterator[Dict[str, Any]]:
        if not self._initialized:
            await self.initialize()
        assert self.agent is not None
        if self.thread_id is not None:
            # Thread-scoped runs key the engine's KV prefix cache by thread,
            # so each turn re-prefills only the conversation suffix
            # (BASELINE config 2; providers without a cache ignore it).
            kwargs.setdefault("prefix_key", self.thread_id)
        async for event in self.agent.run(
            messages,
            model=model or self.default_model,
            temperature=temperature,
            max_tokens=max_tokens,
            **kwargs,
        ):
            yield event
