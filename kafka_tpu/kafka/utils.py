"""Kafka-layer helpers: event-stream re-accumulation and playbook tables.

`MessageAccumulator` rebuilds persistable `Message`s from the agent's
live event stream — the same re-accumulation the reference does inline in
`KafkaAgent.run_with_thread` (src/kafka/base.py:229-299), factored out and
unit-testable.  `playbooks_to_markdown` renders per-thread playbooks into
the markdown table the prompt tier embeds (reference src/kafka/v1.py:330-357).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..core.toolcalls import ToolCallAccumulator
from ..core.types import Message


class MessageAccumulator:
    """Folds the agent event protocol back into ordered `Message`s."""

    def __init__(self) -> None:
        self.messages: List[Message] = []
        self._content: List[str] = []
        self._acc = ToolCallAccumulator()
        self._current_id: Optional[str] = None
        self.final_content: str = ""
        self.done_reason: Optional[str] = None

    def add_event(self, event: Dict[str, Any]) -> None:
        etype = event.get("type")
        if event.get("object") == "chat.completion.chunk":
            self._add_chunk(event)
        elif etype == "tool_result":
            if event.get("done"):
                kind = event.get("kind")
                data = event.get("data")
                text = data if isinstance(data, str) else str(data)
                content = f"Error: {text}" if kind == "error" else text
                self.messages.append(
                    Message(
                        role="tool",
                        content=content,
                        tool_call_id=event.get("tool_call_id"),
                    )
                )
        elif etype == "agent_done":
            self._flush_assistant()
            self.final_content = event.get("final_content") or ""
            self.done_reason = event.get("reason")

    def _add_chunk(self, chunk: Dict[str, Any]) -> None:
        cid = chunk.get("id")
        if self._current_id is not None and cid != self._current_id:
            self._flush_assistant()
        self._current_id = cid
        for choice in chunk.get("choices", []):
            delta = choice.get("delta", {})
            if delta.get("content"):
                self._content.append(delta["content"])
            self._acc.add_deltas(delta.get("tool_calls"))
            if choice.get("finish_reason"):
                self._flush_assistant()

    def _flush_assistant(self) -> None:
        content = "".join(self._content)
        tool_calls = self._acc.result() if self._acc.has_calls else None
        if content or tool_calls:
            self.messages.append(
                Message(
                    role="assistant",
                    content=content or None,
                    tool_calls=tool_calls,
                )
            )
        self._content = []
        self._acc.clear()
        self._current_id = None


def playbooks_to_markdown(playbooks: List[Dict[str, Any]]) -> str:
    """Render playbooks as a markdown section for the system prompt."""
    if not playbooks:
        return ""
    lines = [
        "# Playbooks",
        "",
        "Follow the matching playbook when a task fits its trigger:",
        "",
        "| Playbook | When to use | Steps |",
        "|---|---|---|",
    ]
    for pb in playbooks:
        name = str(pb.get("name", "")).replace("|", "\\|")
        trigger = str(pb.get("trigger", pb.get("description", ""))).replace("|", "\\|")
        content = str(pb.get("content", "")).replace("\n", "<br>").replace("|", "\\|")
        lines.append(f"| {name} | {trigger} | {content} |")
    return "\n".join(lines)
