"""Kafka orchestrator tier: wires LLM, tools, prompts, compaction, threads."""

from .base import KafkaAgent
from .utils import MessageAccumulator, playbooks_to_markdown
from .v1 import KafkaV1Provider

__all__ = [
    "KafkaAgent",
    "KafkaV1Provider",
    "MessageAccumulator",
    "playbooks_to_markdown",
]
