"""Compute ops: norms, rope, attention (XLA reference + Pallas TPU kernels)."""

from .attention import causal_attention, repeat_kv
from .norms import rms_norm
from .rope import apply_rope, rope_cos_sin, rope_frequencies

__all__ = [
    "causal_attention",
    "repeat_kv",
    "rms_norm",
    "apply_rope",
    "rope_cos_sin",
    "rope_frequencies",
]
