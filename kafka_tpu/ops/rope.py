"""Rotary position embeddings (RoPE), including Llama-3.x NTK-by-parts scaling.

Frequencies are computed on the fly from integer position ids rather than
from a precomputed [max_context, dim] table: paged decoding addresses
positions per-sequence, and an on-the-fly gatherless formulation keeps the
decode step free of HBM table lookups (the cos/sin math fuses into the
surrounding elementwise ops under XLA).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax.numpy as jnp


def rope_frequencies(cfg) -> jnp.ndarray:
    """Per-pair inverse frequencies [head_dim//2], with Llama-3 scaling."""
    dim = cfg.head_dim
    inv_freq = 1.0 / (
        cfg.rope_theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    )
    if cfg.rope_scaling_factor is None:
        return inv_freq

    # Llama-3.x "NTK-by-parts": low-frequency components are slowed by
    # `factor`, high-frequency kept, mid-band interpolated smoothly.
    low_freq_wavelen = cfg.rope_original_max_position / cfg.rope_low_freq_factor
    high_freq_wavelen = cfg.rope_original_max_position / cfg.rope_high_freq_factor
    wavelen = 2.0 * math.pi / inv_freq
    scaled = inv_freq / cfg.rope_scaling_factor
    smooth = (cfg.rope_original_max_position / wavelen - cfg.rope_low_freq_factor) / (
        cfg.rope_high_freq_factor - cfg.rope_low_freq_factor
    )
    mid = (1.0 - smooth) * scaled + smooth * inv_freq
    out = jnp.where(wavelen > low_freq_wavelen, scaled, inv_freq)
    out = jnp.where(
        (wavelen <= low_freq_wavelen) & (wavelen >= high_freq_wavelen), mid, out
    )
    return out


def rope_cos_sin(
    positions: jnp.ndarray, inv_freq: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for integer positions [...]: returns [..., head_dim//2]."""
    angles = positions[..., None].astype(jnp.float32) * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Rotate q or k. x: [..., heads, head_dim]; cos/sin broadcast on heads.

    Uses the HF-style "rotate_half" pairing (first half / second half), so
    converted HuggingFace checkpoints produce identical outputs.
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    cos = cos[..., None, :].astype(jnp.float32)
    sin = sin[..., None, :].astype(jnp.float32)
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    r1 = x1f * cos - x2f * sin
    r2 = x2f * cos + x1f * sin
    return jnp.concatenate([r1, r2], axis=-1).astype(x.dtype)
