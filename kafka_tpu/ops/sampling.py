"""Batched token sampling, fully vectorized for the shared decode step.

Every sequence in the continuous-batching step can carry different sampling
parameters (temperature / top-k / top-p / seed) and an optional per-sequence
token mask (constrained decoding for tool-call JSON).  Everything is
branch-free so one jitted kernel serves the whole batch: greedy is the
temperature<=0 limit handled by `jnp.where`, not Python control flow.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .attention import NEG_INF


class SamplingParams(NamedTuple):
    """Per-sequence sampling state, batched [B]."""

    temperature: jnp.ndarray  # [B] float32; <=0 means greedy
    top_k: jnp.ndarray  # [B] int32; 0 disables
    top_p: jnp.ndarray  # [B] float32; 1.0 disables

    @classmethod
    def make(cls, batch: int, temperature=0.0, top_k=0, top_p=1.0):
        return cls(
            temperature=jnp.full((batch,), temperature, jnp.float32),
            top_k=jnp.full((batch,), top_k, jnp.int32),
            top_p=jnp.full((batch,), top_p, jnp.float32),
        )

    def at(self, i: int, temperature=None, top_k=None, top_p=None) -> "SamplingParams":
        """Functional single-slot update (host-side convenience)."""
        t, k, p = self.temperature, self.top_k, self.top_p
        if temperature is not None:
            t = t.at[i].set(temperature)
        if top_k is not None:
            k = k.at[i].set(top_k)
        if top_p is not None:
            p = p.at[i].set(top_p)
        return SamplingParams(t, k, p)


def apply_top_k(logits: jnp.ndarray, top_k: jnp.ndarray) -> jnp.ndarray:
    """Mask logits below the per-row k-th largest. top_k==0 disables."""
    vocab = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    k = jnp.clip(top_k, 1, vocab)
    thresh = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)
    keep = (logits >= thresh) | (top_k[:, None] == 0)
    return jnp.where(keep, logits, NEG_INF)


def apply_top_p(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of the sorted distribution
    with cumulative probability >= top_p. top_p>=1 disables."""
    order = jnp.argsort(logits, axis=-1)[..., ::-1]
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens where the cumulative mass *before* them is < top_p;
    # the top token is always kept so top_p=0 degrades to argmax, not to
    # uniform noise over a fully-masked row
    keep_sorted = ((cum - probs) < top_p[:, None]).at[..., 0].set(True)
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(logits.shape[0])[:, None], order
    ].set(keep_sorted)
    keep = keep | (top_p[:, None] >= 1.0)
    return jnp.where(keep, logits, NEG_INF)


def _filtered_logits(
    logits: jnp.ndarray,
    params: SamplingParams,
    allowed_mask: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Shared masking/temperature/filter pipeline -> (greedy, scaled).

    The top-k/top-p filters each sort the full vocab axis — ~18 ms/step on
    a [8, 128k] batch on TPU, dwarfing the model forward itself — so they
    run under a `lax.cond` that skips them entirely unless some row in the
    batch actually samples with a filter active.  Greedy rows (the agent
    default) never pay for the sorts.
    """
    if allowed_mask is not None:
        usable = jnp.any(allowed_mask, axis=-1, keepdims=True)
        mask = jnp.where(usable, allowed_mask, True)
        logits = jnp.where(mask, logits, NEG_INF)

    greedy_choice = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = logits / temp
    needs_filter = jnp.any(
        (params.temperature > 0.0)
        & ((params.top_k > 0) | (params.top_p < 1.0))
    )

    def filtered(s):
        # ONE shared descending sort serves both filters (each filter
        # sorting separately doubled the dominant cost of this branch)
        order = jnp.argsort(s, axis=-1)[..., ::-1]
        sorted_desc = jnp.take_along_axis(s, order, axis=-1)
        vocab = s.shape[-1]
        rank = jnp.arange(vocab)[None, :]
        # top-k: keep ranks < k (0 disables)
        k = jnp.clip(params.top_k, 1, vocab)[:, None]
        keep_sorted = (rank < k) | (params.top_k[:, None] == 0)
        # top-p over the same sorted order, renormalized over the top-k
        # survivors (sequential top_k -> top_p semantics: top-k keeps a
        # prefix of this order, so masking before the softmax reproduces
        # applying the filters one after the other): keep while the
        # cumulative mass BEFORE the token is < p; the top token survives
        probs = jax.nn.softmax(
            jnp.where(keep_sorted, sorted_desc, NEG_INF), axis=-1
        )
        cum = jnp.cumsum(probs, axis=-1)
        keep_p = ((cum - probs) < params.top_p[:, None]).at[..., 0].set(True)
        keep_p = keep_p | (params.top_p[:, None] >= 1.0)
        keep_sorted = keep_sorted & keep_p
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(s.shape[0])[:, None], order
        ].set(keep_sorted)
        return jnp.where(keep, s, NEG_INF)

    scaled = jax.lax.cond(needs_filter, filtered, lambda s: s, scaled)
    return greedy_choice, scaled


def grammar_allowed_mask(
    fsm_state: jnp.ndarray,
    fsm_g: jnp.ndarray,
    budget_left: jnp.ndarray,
    active: jnp.ndarray,
    token_class: jnp.ndarray,
    trans: jnp.ndarray,
    dist: jnp.ndarray,
    wrap_slack: jnp.ndarray,
) -> jnp.ndarray:
    """[B, V] bool allowed mask from per-lane device FSM states.

    fsm_state [B] int32 (-1 = unconstrained lane), fsm_g [B] grammar index,
    budget_left [B] remaining token budget, token_class [G, V], trans
    [S, C] (-1 illegal), dist [S] shortest tokens-to-done.  Constrained
    lanes within `wrap_slack` tokens of their shortest close restrict to
    distance-decreasing transitions (on-device wrap-up) so a bounded
    generation still parses; unconstrained/inactive lanes get all-True
    rows, which leave the sampler's logits bit-identical to an unmasked
    call.
    """
    S = trans.shape[0]
    on = (fsm_state >= 0) & active
    s = jnp.clip(fsm_state, 0, S - 1)
    row = trans[s]                                   # [B, C]
    legal = row >= 0
    nd = dist[jnp.clip(row, 0, S - 1)]               # [B, C]
    d = dist[s][:, None]                             # [B, 1]
    wrap = budget_left[:, None] <= d + wrap_slack
    keep = legal & (~wrap | (nd < d))
    # a wrap window with no distance-decreasing option (deep jump past the
    # budget) degrades to the plain legal set rather than an empty row
    keep = jnp.where(keep.any(axis=-1, keepdims=True), keep, legal)
    tc = token_class[fsm_g]                          # [B, V]
    mask = jnp.take_along_axis(keep, tc, axis=1)     # [B, V]
    return jnp.where(on[:, None], mask, True)


def grammar_advance(
    fsm_state: jnp.ndarray,
    fsm_g: jnp.ndarray,
    tokens: jnp.ndarray,
    active: jnp.ndarray,
    token_class: jnp.ndarray,
    trans: jnp.ndarray,
) -> jnp.ndarray:
    """Advance each lane's FSM state by one sampled token ([B] int32).
    Inactive/unconstrained lanes keep their state; an illegal token (only
    reachable through the over-tight degrade path) parks the lane at the
    -1 unconstrained sentinel instead of indexing garbage."""
    S = trans.shape[0]
    on = (fsm_state >= 0) & active
    tc = token_class[fsm_g]                          # [B, V]
    cls = jnp.take_along_axis(tc, tokens[:, None], axis=1)[:, 0]
    nxt = trans[jnp.clip(fsm_state, 0, S - 1), cls]
    return jnp.where(on, nxt, fsm_state)


def sample_tokens(
    logits: jnp.ndarray,
    params: SamplingParams,
    key: jax.Array,
    allowed_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Sample one token per row with a shared key. [B, V] f32 -> [B] i32.

    allowed_mask: optional [B, V] bool — False tokens are excluded before
    temperature/filtering (constrained decoding). A fully-False row falls
    back to unconstrained (never emit garbage from an over-tight mask).
    """
    greedy_choice, scaled = _filtered_logits(logits, params, allowed_mask)
    # categorical generates a [B, V] gumbel field (threefry) — measurable
    # per-step HBM/VPU work at a 128k vocab; skip it when every row is
    # greedy (the agent default), same pattern as the filter sorts above
    sampled = jax.lax.cond(
        jnp.any(params.temperature > 0.0),
        lambda s: jax.random.categorical(key, s, axis=-1).astype(jnp.int32),
        lambda s: greedy_choice,
        scaled,
    )
    return jnp.where(params.temperature <= 0.0, greedy_choice, sampled)


def sample_tokens_per_slot(
    logits: jnp.ndarray,
    params: SamplingParams,
    keys: jax.Array,
    allowed_mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Like sample_tokens but with one PRNG key per row ([B] key array).

    Per-slot keys make each request's sampling deterministic in
    (seed, position) regardless of what else shares the continuous-batching
    step — requests are reproducible under preemption and re-batching.
    """
    greedy_choice, scaled = _filtered_logits(logits, params, allowed_mask)
    sampled = jax.lax.cond(
        jnp.any(params.temperature > 0.0),
        lambda s: jax.vmap(
            lambda k, row: jax.random.categorical(k, row).astype(jnp.int32)
        )(keys, s),
        lambda s: greedy_choice,
        scaled,
    )
    return jnp.where(params.temperature <= 0.0, greedy_choice, sampled)
