"""Flash prefill kernel over the paged KV pool.

The XLA prefill path materializes the full [Hq, S, C] score tensor per
layer — at an 8k window that is half a gigabyte of f32 per chunk per
layer.  This kernel streams the KV window in page chunks with online
softmax (flash attention), so peak memory is O(q_block x kv_chunk) and
HBM traffic is one pass over the valid window per q block.

Structure mirrors the decode kernel (paged_attention.py):

* merged-lane pool [TOTAL_SLOTS, Hkv*D] (the DMA lane-alignment contract);
* GQA via the block-diagonal q expansion — rows are (q position, q head)
  pairs, each row's D lanes sit in its kv head's block, one full-width
  MXU matmul per chunk, per-head lanes sliced out by the caller;
* grid = (num_q_blocks,); per block, a dynamic fori_loop over the kv
  chunks the causal mask can reach (a q block early in the prompt skips
  the chunks after it entirely), each chunk double-buffer DMA'd.

Causality: the engine writes the whole chunk's KV to the pool before
attention, so kv slots carry absolute positions page-order; a query at
absolute position p attends kv positions <= p, bounded by the written
total (start + chunk_len).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _prefill_kernel(
    # scalar prefetch
    page_row_ref,   # [P] i32 physical pages of this sequence
    bounds_ref,     # [2] i32: (start, chunk_len)
    # inputs
    qx_ref,         # [QB*Hq, Hkv*D] VMEM block (block-diagonal expanded)
    k_pages_hbm,    # [num_pages, ps, Hkv*D] ANY
    v_pages_hbm,    # [num_pages, ps, Hkv*D] ANY
    out_ref,        # [QB*Hq, Hkv*D] VMEM block
    # scratch
    kbuf, vbuf, ksem, vsem,
    m_ref, l_ref, acc_ref,
    *,
    num_q_heads: int,
    page_size: int,
    pages_per_chunk: int,
    q_block: int,
    scale: float,
):
    qb = pl.program_id(0)
    ps, cp, hq = page_size, pages_per_chunk, num_q_heads
    chunk = cp * ps
    start = bounds_ref[0]
    chunk_len = bounds_ref[1]
    # kv positions this q block may attend: all of [0, kv_hi) — the block's
    # last real query position + 1, already bounded by the written total
    kv_hi = start + jnp.minimum((qb + 1) * q_block, chunk_len)
    n_pages = pl.cdiv(kv_hi, ps)
    n_chunks = pl.cdiv(n_pages, cp)

    def issue(c, slot):
        for j in range(cp):
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_row_ref[c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).start()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).start()

    def wait(c, slot):
        for j in range(cp):
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_row_ref[c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).wait()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).wait()

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    issue(0, 0)

    rows = q_block * hq
    # absolute q position of each folded row (row = q_idx * Hq + head)
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0)
    q_pos = start + qb * q_block + row_ids // hq  # [rows, 1]

    def body(c, carry):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            issue(c + 1, jax.lax.rem(c + 1, 2))

        wait(c, slot)

        col_ids = jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)
        kv_pos = c * chunk + col_ids  # [1, chunk]
        mask = (q_pos >= kv_pos) & (kv_pos < kv_hi)  # [rows, chunk]
        # column-shaped validity built directly (Mosaic cannot transpose a
        # boolean vector)
        col_iota = jax.lax.broadcasted_iota(jnp.int32, (chunk, 1), 0)
        col_valid = col_iota < (kv_hi - c * chunk)

        kc = kbuf[slot].astype(jnp.float32)  # [chunk, HD]
        # zero junk V rows (never-DMA'd NaNs poison 0-weight matmuls)
        vc = jnp.where(col_valid, vbuf[slot].astype(jnp.float32), 0.0)
        qx = qx_ref[...].astype(jnp.float32)  # [rows, HD]
        s = (
            jax.lax.dot_general(
                qx, kc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [rows, chunk]
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(mask, pexp, 0.0)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, vc,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new
        return carry

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[...], 1e-30)
    out_ref[...] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "pages_per_chunk", "q_block", "scale",
                     "interpret"),
)
def paged_prefill_attention(
    q: jnp.ndarray,          # [S, Hq, D] roped queries of this chunk
    k_pool: jnp.ndarray,     # [TOTAL_SLOTS, Hkv*D] merged-lane pool
    v_pool: jnp.ndarray,
    page_row: jnp.ndarray,   # [P] i32 pages of this sequence
    start: jnp.ndarray,      # scalar i32: chunk's first absolute position
    chunk_len: jnp.ndarray,  # scalar i32: real tokens in the chunk
    *,
    page_size: int,
    pages_per_chunk: int = 8,
    q_block: int = 64,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Flash attention of one prefill chunk against the paged window.

    Returns [S, Hq, D] in q.dtype.  Rows past chunk_len are garbage (their
    KV went to the trash page) — same contract as the XLA path, which only
    samples from the last real row.
    """
    S, Hq, D = q.shape
    HD = k_pool.shape[1]
    Hkv = HD // D
    G = Hq // Hkv
    if scale is None:
        scale = D**-0.5
    # Scoped-VMEM bound: the kernel's per-block footprint scales with
    # rows = q_block * Hq (qx/out pipeline buffers, f32 accumulator, and
    # the [rows, chunk] softmax temporaries).  rows = 2048 measured
    # 17.91 MB of scoped VMEM against the 16 MB core limit (Mosaic
    # stack-OOM at compile, first hit by the 2048-token prefill bucket at
    # 32 heads); rows <= ~1024 keeps ~9 MB with headroom for the DMA
    # buffers.  The cap is rounded DOWN to a power of two so it divides
    # the power-of-two chunk buckets for any head count (1024//24 = 42
    # would fail S % qb for every bucket).
    cap = max(8, 1024 // Hq)
    cap = 1 << (cap.bit_length() - 1)
    qb = min(q_block, S, cap)
    if S % qb:
        raise ValueError(f"chunk length {S} not divisible by q_block {qb}")
    cp = min(pages_per_chunk, page_row.shape[0])
    k_pages = k_pool.reshape(-1, page_size, HD)
    v_pages = v_pool.reshape(-1, page_size, HD)

    # block-diagonal expansion, rows = (q position, head) pairs
    kv_of_q = jnp.repeat(jnp.arange(Hkv), G)  # [Hq]
    qx = jnp.zeros((S, Hq, Hkv, D), q.dtype)
    qx = qx.at[:, jnp.arange(Hq), kv_of_q].set(q)
    qx = qx.reshape(S * Hq, HD)
    bounds = jnp.stack([jnp.asarray(start, jnp.int32),
                        jnp.asarray(chunk_len, jnp.int32)])

    rows = qb * Hq
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S // qb,),
        in_specs=[
            pl.BlockSpec((rows, HD), lambda b, pr, bd: (b, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((rows, HD), lambda b, pr, bd: (b, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, cp * page_size, HD), k_pool.dtype),
            pltpu.VMEM((2, cp * page_size, HD), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, 1), jnp.float32),
            pltpu.VMEM((rows, HD), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _prefill_kernel,
        num_q_heads=Hq,
        page_size=page_size,
        pages_per_chunk=cp,
        q_block=qb,
        scale=scale,
    )
    out_wide = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S * Hq, HD), q.dtype),
        interpret=interpret,
    )(page_row, bounds, qx, k_pages, v_pages)
    return out_wide.reshape(S, Hq, Hkv, D)[:, jnp.arange(Hq), kv_of_q]
