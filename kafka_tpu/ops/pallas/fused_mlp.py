"""Fused decode-MLP block: rmsnorm + SwiGLU + residual in one kernel.

MEASURED OUTCOME (round 5, scripts/bench_fused_mlp.py on the v5e chip,
device-resident timing with RTT differencing): this kernel does NOT beat
XLA's own formulation at decode shapes and is therefore NOT wired into
the serving path.  At llama-3.2-1b shapes (H=2048, F=8192, L=16, B=8),
16-layer MLP stack per pass:

    XLA 3-einsum scan    2.235 ms   (720 GB/s of weight stream)
    this kernel          2.721 ms   (592 GB/s)
    XLA int8 scan        1.058 ms   (762 GB/s effective)
    this kernel int8     1.768 ms   (456 GB/s)

i.e. XLA already streams the MLP trio at ~88-93% of the chip's nominal
819 GB/s — there is no inter-op bubble for a handable fusion to reclaim,
and Mosaic's small-batch (B=8 sublane) matmul pipeline is measurably
weaker than XLA's.  The kernel is kept in-tree, tested for numerics
(tests/test_fused_mlp.py), as the recorded ablation VERDICT r4 #1 called
for if the fusion lever turned out to be a dead end on this platform —
plus the per-output-channel post-scaling trick it demonstrates (see
below) which int8 serving inherits.

The original rationale (COVERAGE roofline): the b8 decode step spends
~4.1 ms in the layer sweep against a 2.4 ms weight-streaming floor.  The
MLP trio (wg/wu/wd) is ~85% of a Llama layer's weight bytes; as three
separate XLA matmuls with elementwise ops between them, each op would pay
its own pipeline ramp — except measurement shows XLA's scheduler already
overlaps them to roofline.  Design of the kernel, kept for reference:

  out = h + wd^T( silu(nx @ wg_t) * (nx @ wu_t) ),   nx = rmsnorm(h) * ln

* grid = (F // block_f,): one program per F-tile.  Step 0 computes the
  f32 rmsnorm into VMEM scratch (persistent across the sequential TPU
  grid); every step contracts its [H, bf] wg/wu tiles and [bf, H] wd tile,
  accumulating the down-projection in f32 scratch; the last step adds the
  residual and writes out.
* block_f adapts to VMEM: largest divisor of F (multiple of 128) keeping
  the double-buffered tile set under ~10 MB of the ~16 MB budget.
* int8 (models/quant.py QTensors): tiles arrive int8 — HALF the HBM
  stream — and dequantize on the VPU per tile with the same
  (q * s_f32) -> bf16 element rounding as the XLA path's fused dequant.
* batch stays as the block's sublane dim ([B, H] blocks, B = max_batch):
  decode batches are 8-64 rows, far under the MXU's 128 — these matmuls
  are bandwidth-bound, which is exactly why the DMA pipeline is the lever.

Numerics: matches the XLA path op-for-op (f32 norm, bf16 matmul operands
with f32 accumulation cast once per projection, bf16 silu/residual) but
not bit-for-bit (accumulation order differs tile-wise); engines under
either backend are token-compared in tests/test_fused_mlp.py, the same
contract the paged-attention kernel ships under.

No reference analog: the reference ran no local model (its compute lived
behind src/llm/portkey.py); SURVEY §2.3 sanctions Pallas kernels for the
serving hot loop.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# double-buffered (wg + wu + wd) tile budget; VMEM is ~16 MB/core and the
# persistent scratch (nx/acc/h blocks) + output need the rest
_TILE_BUDGET_BYTES = 10 * 1024 * 1024


def pick_block_f(H: int, F: int, weight_bytes: int) -> Optional[int]:
    """Largest 128-multiple divisor of F whose double-buffered tile set
    (2 buffers x 3 weights x [H or F-tile] x block_f) fits the budget."""
    best = None
    bf = 128
    while bf <= F:
        if F % bf == 0 and 2 * 3 * H * bf * weight_bytes <= _TILE_BUDGET_BYTES:
            best = bf
        bf *= 2
    return best


def _kernel(
    h_ref,      # [B, H] activation dtype — residual input
    ln_ref,     # [1, H] norm weight
    wg_ref,     # [H, bf] (bf16 or int8)
    wu_ref,     # [H, bf]
    wd_ref,     # [bf, H]
    sg_ref,     # [1, bf] f32 or None
    su_ref,     # [1, bf] f32 or None
    sd_ref,     # [1, H] f32 or None
    out_ref,    # [B, H]
    nx_ref,     # scratch [B, H] activation dtype — normed input
    acc_ref,    # scratch [B, H] f32 — down-projection accumulator
    *,
    eps: float,
    quantized: bool,
):
    i = pl.program_id(0)
    dt = h_ref.dtype

    @pl.when(i == 0)
    def _prologue():
        x32 = h_ref[...].astype(jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        normed = x32 * jax.lax.rsqrt(var + eps)
        nx_ref[...] = (normed * ln_ref[...].astype(jnp.float32)).astype(dt)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def mm(x, w_ref):
        # int8 operands upcast to the activation dtype at the MXU's door
        # (exact for |q| <= 127); per-output-channel scales are applied to
        # the small OUTPUT, never the [H, tile] operand — they commute out
        # of the contraction (the same algebra the int8 logits head uses,
        # models/llama.py), and operand-side dequant is VPU-bound at a
        # million elements per tile (measured 1.66x slower end-to-end)
        return jax.lax.dot_general(
            x, w_ref[...].astype(dt),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    nx = nx_ref[...]
    g = mm(nx, wg_ref)
    u = mm(nx, wu_ref)
    if quantized:
        g = g * sg_ref[...]  # [B, bf] * [1, bf] f32
        u = u * su_ref[...]
    g = g.astype(dt)
    u = u.astype(dt)
    # silu with the sigmoid in f32: Mosaic mis-lowers logistic on bf16
    # vectors (vector.broadcast f32->bf16 verification failure); one extra
    # f32->bf16 rounding vs the XLA path's bf16 silu, inside tolerance
    g32 = g.astype(jnp.float32)
    p = (g32 * jax.nn.sigmoid(g32)).astype(dt) * u
    acc_ref[...] += mm(p, wd_ref)

    @pl.when(i == pl.num_programs(0) - 1)
    def _epilogue():
        # residual add in the activation dtype — the XLA path's h + mlp(x).
        # wd's per-output-H scale is constant across F-tiles: applied once
        # to the finished f32 accumulator.
        acc = acc_ref[...]
        if quantized:
            acc = acc * sd_ref[...]
        out_ref[...] = h_ref[...] + acc.astype(dt)


@functools.partial(
    jax.jit, static_argnames=("eps", "block_f", "interpret")
)
def fused_mlp_block(
    h: jnp.ndarray,            # [B, H] activations (residual stream)
    ln_w: jnp.ndarray,         # [H] rmsnorm weight
    wg: jnp.ndarray,           # [H, F] bf16/int8
    wu: jnp.ndarray,           # [H, F]
    wd: jnp.ndarray,           # [F, H]
    sg: Optional[jnp.ndarray] = None,   # [1, F] f32 scales (int8 only)
    su: Optional[jnp.ndarray] = None,   # [1, F]
    sd: Optional[jnp.ndarray] = None,   # [1, H]
    *,
    eps: float,
    block_f: Optional[int] = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """h + SwiGLU_mlp(rmsnorm(h) * ln_w).  Returns [B, H] in h.dtype."""
    B, H = h.shape
    F = wg.shape[1]
    quantized = sg is not None
    if block_f is None:
        block_f = pick_block_f(H, F, wg.dtype.itemsize)
    if block_f is None or F % block_f:
        raise ValueError(
            f"no F-tile fits: H={H} F={F} itemsize={wg.dtype.itemsize}"
        )
    grid = (F // block_f,)

    full = lambda i: (0, 0)  # noqa: E731 — constant-index (resident) block
    specs = [
        pl.BlockSpec((B, H), full),                      # h
        pl.BlockSpec((1, H), full),                      # ln
        pl.BlockSpec((H, block_f), lambda i: (0, i)),    # wg tile
        pl.BlockSpec((H, block_f), lambda i: (0, i)),    # wu tile
        pl.BlockSpec((block_f, H), lambda i: (i, 0)),    # wd tile
    ]
    args = [h, ln_w.reshape(1, H)]
    args += [wg, wu, wd]
    if quantized:
        specs += [
            pl.BlockSpec((1, block_f), lambda i: (0, i)),  # sg tile
            pl.BlockSpec((1, block_f), lambda i: (0, i)),  # su tile
            pl.BlockSpec((1, H), full),                    # sd
        ]
        args += [sg, su, sd]
    else:
        # pallas has no optional refs: thread zero-size placeholders
        specs += [
            pl.BlockSpec((1, 1), full),
            pl.BlockSpec((1, 1), full),
            pl.BlockSpec((1, 1), full),
        ]
        z = jnp.zeros((1, 1), jnp.float32)
        args += [z, z, z]

    return pl.pallas_call(
        functools.partial(_kernel, eps=eps, quantized=quantized),
        grid=grid,
        in_specs=specs,
        out_specs=pl.BlockSpec((B, H), full),
        out_shape=jax.ShapeDtypeStruct((B, H), h.dtype),
        scratch_shapes=[
            pltpu.VMEM((B, H), h.dtype),       # nx
            pltpu.VMEM((B, H), jnp.float32),   # acc
        ],
        interpret=interpret,
    )(*args)
