"""Pallas TPU kernels — the framework's native tier.

The reference has no native code at all (SURVEY §2.3: its compute lived
behind remote gateways); these kernels are the TPU-native equivalent of the
CUDA kernels a GPU serving stack would carry.  Each kernel is validated
against the XLA reference formulation in ops/attention.py, which remains the
numerics ground truth and the portable fallback (CPU tests, non-TPU
platforms, and sharded meshes where GSPMD cannot partition a custom call).

Selection is driven by `ModelConfig.attention_backend`:
  "auto"   — pallas on single-device TPU paged decode, xla otherwise
  "pallas" — force the kernels (interpret mode off-TPU; tests use this)
  "xla"    — force the reference path
"""

from .flash_prefill import paged_prefill_attention
from .paged_attention import paged_decode_attention

__all__ = ["paged_decode_attention", "paged_prefill_attention"]
