"""Pallas TPU kernels — the framework's native tier.

The reference has no native code at all (SURVEY §2.3: its compute lived
behind remote gateways); these kernels are the TPU-native equivalent of the
CUDA kernels a GPU serving stack would carry.  Each kernel is validated
against the XLA reference formulation in ops/attention.py, which remains the
numerics ground truth and the portable fallback (CPU tests, non-TPU
platforms, and mesh layouts the per-shard kernel cannot express).

Selection is driven by `ModelConfig.attention_backend`:
  "auto"   — pallas for paged decode on single-device TPU AND on pure
             tp(/tq) meshes whose head split lines up per-shard
             (pallas_mesh_ok: shard_map runs the kernel per device);
             xla otherwise
  "pallas" — force the kernels (interpret mode off-TPU; tests use this)
  "xla"    — force the reference path
"""

from .flash_prefill import paged_prefill_attention
from .paged_attention import (
    paged_decode_attention,
    paged_decode_attention_int8,
    paged_decode_attention_int8_sharded,
    paged_decode_attention_sharded,
    paged_verify_attention,
    paged_verify_attention_sharded,
    pallas_mesh_ok,
)

__all__ = [
    "paged_decode_attention",
    "paged_decode_attention_int8",
    "paged_decode_attention_int8_sharded",
    "paged_decode_attention_sharded",
    "paged_prefill_attention",
    "paged_verify_attention",
    "paged_verify_attention_sharded",
    "pallas_mesh_ok",
]
