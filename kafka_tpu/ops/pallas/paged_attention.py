"""Paged-attention decode kernel.

One decode step attends each sequence's KV window, which lives scattered
across physical pages of the shared pool (runtime/kv_cache.py).  The XLA
reference path materializes the whole [B, C, Hkv, D] window per layer via a
gather — it reads the *configured* window regardless of how long each
sequence actually is, and round-trips the gathered copy through HBM.  This
kernel walks each sequence's page list directly:

* grid = (B,): one program per sequence.  The page table and sequence
  lengths ride in as **scalar-prefetch** arguments so the kernel can
  dereference physical page ids at runtime.
* the kernel iterates only over the sequence's *valid* pages — a dynamic
  `fori_loop` over chunks of `pages_per_chunk` pages, each chunk landed in
  VMEM by manually issued per-page async DMAs, double-buffered so chunk
  c+1's copies overlap chunk c's compute.  A sequence 300 tokens into an
  8k window reads 300 tokens' worth of KV, not 8k.
* online softmax (m, l, acc) in VMEM scratch across chunks.  GQA is an
  unrolled per-kv-head loop over query groups — no repeat_kv
  materialization.

Layout contract: the pool stores each slot's row as Hkv*D merged lanes
([TOTAL_SLOTS, Hkv*D]) — Mosaic requires DMA slices to be lane-tile (128)
aligned, so per-head layouts with D=64 cannot be page-DMA'd; the merged row
(512 lanes for 8x64) can.  Mosaic also cannot unfold merged lanes back to
heads in-kernel, so GQA is expressed *algebraically*: the caller expands q
block-diagonally to [Hq, Hkv*D] (zeros outside each query head's own
kv-head lane block), QK^T over merged rows then contracts exactly the right
D lanes per head in one full-width MXU matmul, and the PV product yields
[Hq, Hkv*D] from which the caller slices each row's own kv-head block.

Numerics ground truth: ops.attention.causal_attention (tests compare both
paths on random page layouts).  f32 accumulation throughout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import shard_map

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, P] i32
    seq_lens_ref,    # [B] i32
    # inputs
    q_ref,        # [1, Hq, Hkv*D] VMEM block — block-diagonal expanded q
    k_pages_hbm,  # [num_pages, ps, Hkv*D] in HBM/ANY
    v_pages_hbm,  # [num_pages, ps, Hkv*D] in HBM/ANY
    out_ref,      # [1, Hq, Hkv*D] VMEM block — caller slices per-head lanes
    # scratch
    kbuf,     # [2, CP*ps, Hkv*D] pool dtype
    vbuf,     # [2, CP*ps, Hkv*D]
    ksem,     # DMA sems [2, CP]
    vsem,     # DMA sems [2, CP]
    m_ref,    # [Hq, 1] f32 running max
    l_ref,    # [Hq, 1] f32 running denominator
    acc_ref,  # [Hq, Hkv*D] f32 running numerator
    *,
    page_size: int,
    pages_per_chunk: int,
    scale: float,
):
    b = pl.program_id(0)
    ps, cp = page_size, pages_per_chunk
    chunk = cp * ps
    # query position is seq_len; it attends positions <= seq_len
    n_valid = seq_lens_ref[b] + 1
    n_pages = pl.cdiv(n_valid, ps)
    n_chunks = pl.cdiv(n_pages, cp)

    def issue(c, slot):
        for j in range(cp):  # static unroll; per-page scattered DMA
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_table_ref[b, c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).start()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).start()

    def wait(c, slot):
        for j in range(cp):
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_table_ref[b, c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).wait()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).wait()

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    issue(0, 0)

    def body(c, carry):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            issue(c + 1, jax.lax.rem(c + 1, 2))

        wait(c, slot)

        # mask: local slot index within the chunk vs remaining valid slots
        remaining = n_valid - c * chunk
        local = jax.lax.broadcasted_iota(jnp.int32, (1, chunk), dimension=1)
        slot_mask = local < remaining  # [1, chunk]
        local_col = jax.lax.broadcasted_iota(jnp.int32, (chunk, 1), dimension=0)
        col_mask = local_col < remaining  # [chunk, 1]

        # Merged-lane compute: q arrives pre-expanded block-diagonally
        # ([Hq, Hkv*D], zeros outside each query head's own kv-head lane
        # block), so QK^T over the full merged row contracts exactly each
        # head's D lanes — one MXU matmul for all heads, no in-kernel
        # reshape (Mosaic cannot unfold merged lanes).  Rows past the valid
        # range were never DMA'd; zero V before the PV matmul — a NaN there
        # would poison the accumulator even under zero probability weight
        # (0 * NaN = NaN).  K needs no masking: its scores are overwritten
        # by the NEG_INF mask.
        kc = kbuf[slot].astype(jnp.float32)  # [chunk, HD]
        vc = jnp.where(col_mask, vbuf[slot].astype(jnp.float32), 0.0)
        qx = q_ref[0].astype(jnp.float32)  # [Hq, HD] block-diagonal
        s = (
            jax.lax.dot_general(
                qx, kc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Hq, chunk]
        s = jnp.where(slot_mask, s, NEG_INF)

        m_prev = m_ref[...]  # [Hq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(slot_mask, pexp, 0.0)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
        # [Hq, HD]: each row holds every kv head's weighted V; the caller
        # slices out the row's own kv-head lane block.
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, vc,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new
        return carry

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[...], 1e-30)
    out_ref[0, :, :] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "pages_per_chunk", "scale", "interpret"),
)
def paged_decode_attention(
    q: jnp.ndarray,            # [B, Hq, D] — one query token per sequence
    k_pool: jnp.ndarray,       # [TOTAL_SLOTS, Hkv*D] merged-lane pool
    v_pool: jnp.ndarray,       # [TOTAL_SLOTS, Hkv*D]
    page_table: jnp.ndarray,   # [B, P] i32 physical page ids
    seq_lens: jnp.ndarray,     # [B] i32 tokens already cached (query pos)
    *,
    page_size: int,
    pages_per_chunk: int = 8,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Decode-step attention straight off the paged KV pool.

    Returns [B, Hq, D] in q.dtype.  Inactive batch lanes (whose table rows
    point at the trash page) produce garbage rows that the engine discards —
    same contract as the XLA gather path.
    """
    B, Hq, D = q.shape
    HD = k_pool.shape[1]
    Hkv = HD // D
    G = Hq // Hkv
    P = page_table.shape[1]
    if scale is None:
        scale = D**-0.5
    cp = min(pages_per_chunk, P)
    k_pages = k_pool.reshape(-1, page_size, HD)
    v_pages = v_pool.reshape(-1, page_size, HD)

    # Block-diagonal query expansion (see module docstring): qx[b, qh] has
    # q[b, qh] in its own kv head's D-lane block and zeros elsewhere.
    kv_of_q = jnp.repeat(jnp.arange(Hkv), G)  # [Hq]
    qx = jnp.zeros((B, Hq, Hkv, D), q.dtype)
    qx = qx.at[:, jnp.arange(Hq), kv_of_q].set(q)
    qx = qx.reshape(B, Hq, HD)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, HD), lambda b, pt, sl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hq, HD), lambda b, pt, sl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, cp * page_size, HD), k_pool.dtype),
            pltpu.VMEM((2, cp * page_size, HD), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.VMEM((Hq, 1), jnp.float32),
            pltpu.VMEM((Hq, 1), jnp.float32),
            pltpu.VMEM((Hq, HD), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        pages_per_chunk=cp,
        scale=scale,
    )
    out_wide = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, HD), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, qx, k_pages, v_pages)
    # each query row's result lives in its own kv head's lane block
    return out_wide.reshape(B, Hq, Hkv, D)[:, jnp.arange(Hq), kv_of_q]


def _verify_kernel(
    # scalar prefetch
    page_table_ref,  # [B, P] i32
    seq_lens_ref,    # [B] i32 tokens cached BEFORE this step
    q_lens_ref,      # [B] i32 valid queries this step (cand_len + 1)
    # inputs
    q_ref,        # [1, S*Hq, Hkv*D] VMEM — block-diagonal expanded q
    k_pages_hbm,  # [num_pages, ps, Hkv*D]
    v_pages_hbm,  # [num_pages, ps, Hkv*D]
    out_ref,      # [1, S*Hq, Hkv*D] VMEM
    # scratch
    kbuf, vbuf, ksem, vsem, m_ref, l_ref, acc_ref,
    *,
    page_size: int,
    pages_per_chunk: int,
    n_queries: int,  # S = speculative_k + 1 (static)
    heads: int,      # Hq (static)
    scale: float,
):
    """Speculative-verify attention: S = K+1 query tokens per sequence in
    one kernel launch (the decode kernel generalized from one query row
    group to S of them).  Query j sits at position seq_len + j and
    attends positions <= seq_len + j — per-ROW causal masking over the
    merged-lane score matrix (rows are (query, head) pairs, S-major), on
    top of the same double-buffered per-page DMA walk the decode kernel
    does.  One weight... one KV-stream serves all S queries — exactly the
    amortization speculative decoding exists for."""
    b = pl.program_id(0)
    ps, cp = page_size, pages_per_chunk
    chunk = cp * ps
    rows = n_queries * heads
    # valid KV = previously cached tokens + this step's q_len fresh writes
    n_valid = seq_lens_ref[b] + q_lens_ref[b]
    n_pages = pl.cdiv(n_valid, ps)
    n_chunks = pl.cdiv(n_pages, cp)

    def issue(c, slot):
        for j in range(cp):
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_table_ref[b, c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).start()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).start()

    def wait(c, slot):
        for j in range(cp):
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_table_ref[b, c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).wait()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).wait()

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    issue(0, 0)

    def body(c, carry):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            issue(c + 1, jax.lax.rem(c + 1, 2))

        wait(c, slot)

        remaining = n_valid - c * chunk
        # per-(query, head)-row causal mask: row r is query r // heads at
        # position seq_len + r // heads; column g is global slot
        # c*chunk + local — allow g <= qpos AND g < n_valid (garbage
        # queries past q_len are clamped to the valid window so stale
        # never-DMA'd rows cannot leak in; their outputs are discarded)
        col = jax.lax.broadcasted_iota(jnp.int32, (rows, chunk), 1)
        row = jax.lax.broadcasted_iota(jnp.int32, (rows, chunk), 0)
        qpos = seq_lens_ref[b] + row // heads
        g = c * chunk + col
        allow = (g <= qpos) & (col < remaining)  # [rows, chunk]
        local_col = jax.lax.broadcasted_iota(jnp.int32, (chunk, 1), 0)
        col_mask = local_col < remaining  # [chunk, 1] — zero garbage V

        kc = kbuf[slot].astype(jnp.float32)  # [chunk, HD]
        vc = jnp.where(col_mask, vbuf[slot].astype(jnp.float32), 0.0)
        qx = q_ref[0].astype(jnp.float32)  # [rows, HD] block-diagonal
        s = (
            jax.lax.dot_general(
                qx, kc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [rows, chunk]
        s = jnp.where(allow, s, NEG_INF)

        m_prev = m_ref[...]  # [rows, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(allow, pexp, 0.0)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(pexp, axis=-1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, vc,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new
        return carry

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[...], 1e-30)
    out_ref[0, :, :] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "pages_per_chunk", "scale", "interpret"),
)
def paged_verify_attention(
    q: jnp.ndarray,            # [B, S, Hq, D] — K+1 query tokens per seq
    k_pool: jnp.ndarray,       # [TOTAL_SLOTS, Hkv*D] merged-lane pool
    v_pool: jnp.ndarray,       # [TOTAL_SLOTS, Hkv*D]
    page_table: jnp.ndarray,   # [B, P] i32
    seq_lens: jnp.ndarray,     # [B] i32 tokens cached before the step
    q_lens: jnp.ndarray,       # [B] i32 valid queries (cand_len + 1)
    *,
    page_size: int,
    pages_per_chunk: int = 8,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Speculative-verify attention off the paged pool: [B, S, Hq, D] in
    q.dtype, each query row causally masked to its own position.  The
    engine's verify step has already written the S input tokens' KV, so
    the kernel walks seq_len + q_len valid slots per sequence.  Rows for
    queries past q_len produce garbage the caller discards — same
    contract as inactive lanes in the decode kernel."""
    B, S, Hq, D = q.shape
    HD = k_pool.shape[1]
    Hkv = HD // D
    G = Hq // Hkv
    P = page_table.shape[1]
    if scale is None:
        scale = D**-0.5
    cp = min(pages_per_chunk, P)
    k_pages = k_pool.reshape(-1, page_size, HD)
    v_pages = v_pool.reshape(-1, page_size, HD)

    # block-diagonal query expansion, per query token (see module
    # docstring): row (s, qh) holds q[b, s, qh] in its own kv head's
    # D-lane block
    kv_of_q = jnp.repeat(jnp.arange(Hkv), G)  # [Hq]
    qx = jnp.zeros((B, S, Hq, Hkv, D), q.dtype)
    qx = qx.at[:, :, jnp.arange(Hq), kv_of_q].set(q)
    qx = qx.reshape(B, S * Hq, HD)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, S * Hq, HD), lambda b, pt, sl, ql: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, S * Hq, HD),
                               lambda b, pt, sl, ql: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, cp * page_size, HD), k_pool.dtype),
            pltpu.VMEM((2, cp * page_size, HD), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.VMEM((S * Hq, 1), jnp.float32),
            pltpu.VMEM((S * Hq, 1), jnp.float32),
            pltpu.VMEM((S * Hq, HD), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _verify_kernel,
        page_size=page_size,
        pages_per_chunk=cp,
        n_queries=S,
        heads=Hq,
        scale=scale,
    )
    out_wide = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S * Hq, HD), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, q_lens, qx, k_pages, v_pages)
    return out_wide.reshape(B, S, Hq, Hkv, D)[
        :, :, jnp.arange(Hq), kv_of_q
    ]


def paged_verify_attention_sharded(
    mesh,
    q: jnp.ndarray,            # [B, S, Hq, D]
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    seq_lens: jnp.ndarray,
    q_lens: jnp.ndarray,
    *,
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """The verify kernel on a tp(/tq) mesh — same per-shard head-split
    contract as paged_decode_attention_sharded (caller must have passed
    pallas_mesh_ok)."""
    from jax.sharding import PartitionSpec as P

    q_ax = ("tp", "tq") if mesh.shape.get("tq", 1) > 1 else "tp"
    fn = shard_map(
        functools.partial(
            paged_verify_attention, page_size=page_size,
            interpret=interpret,
        ),
        mesh=mesh,
        in_specs=(P(None, None, q_ax, None), P(None, "tp"), P(None, "tp"),
                  P(None, None), P(None), P(None)),
        out_specs=P(None, None, q_ax, None),
        check_vma=False,
    )
    return fn(q, k_pool, v_pool, page_table, seq_lens, q_lens)


def pallas_mesh_ok(mesh, num_heads: int, num_kv_heads: int) -> bool:
    """Can the decode kernel run per-shard on this mesh via shard_map?

    GSPMD cannot partition a Pallas custom call, but shard_map runs it
    per device on local shards.  The head split must line up with
    parallel/sharding.py's layout:

    * only the tensor axes may be >1 (dp/sp/pp/ep shard things the
      kernel's per-shard view cannot express);
    * kv heads split over "tp" (tp | Hkv), q heads over ("tp","tq");
    * per-shard GQA must keep the kernel's contiguous q->kv map: any
      local kv-head count works when tq == 1 (plain Megatron split), but
      a grouped mesh (tq > 1) needs exactly ONE kv head per shard — the
      same invariant ring_attention's _prefill_sharded enforces.
    """
    if mesh is None or mesh.size == 1:
        return True
    tp = mesh.shape.get("tp", 1)
    tq = mesh.shape.get("tq", 1)
    if tp * tq != mesh.size or tp <= 1:
        return False
    if num_kv_heads % tp or num_heads % (tp * tq):
        return False
    if (num_heads // num_kv_heads) % tq:
        return False
    return tq == 1 or num_kv_heads // tp == 1


def paged_decode_attention_sharded(
    mesh,
    q: jnp.ndarray,            # [B, Hq, D]
    k_pool: jnp.ndarray,       # [TOTAL_SLOTS, Hkv*D]
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,   # [B, P]
    seq_lens: jnp.ndarray,     # [B]
    *,
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """The decode kernel on a tp(/tq) mesh: one kernel per device over its
    local head shard, zero collectives (heads are embarrassingly parallel
    in attention; the surrounding wo einsum pays the existing psum).

    q heads ride ("tp","tq") and the pool's merged kv axis rides "tp",
    matching the engine's placement (parallel/sharding.py), so shard_map
    introduces no resharding.  check_vma is off: pallas_call's out_shape
    carries no varying-axes metadata.  Caller must have passed
    pallas_mesh_ok.
    """
    from jax.sharding import PartitionSpec as P

    q_ax = ("tp", "tq") if mesh.shape.get("tq", 1) > 1 else "tp"
    fn = shard_map(
        functools.partial(
            paged_decode_attention, page_size=page_size, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(P(None, q_ax, None), P(None, "tp"), P(None, "tp"),
                  P(None, None), P(None)),
        out_specs=P(None, q_ax, None),
        check_vma=False,
    )
    return fn(q, k_pool, v_pool, page_table, seq_lens)


def _decode_kernel_int8(
    # scalar prefetch
    page_table_ref,  # [B, P] i32
    seq_lens_ref,    # [B] i32
    # inputs
    q_ref,        # [1, Hq, Hkv*D] VMEM — block-diagonal expanded q
    ksw_ref,      # [1, NC, chunk] f32 — k per-slot scales, chunk-major
    vsw_ref,      # [1, NC, chunk] f32 — v per-slot scales
    k_pages_hbm,  # [num_pages, ps, Hkv*D] int8 in HBM/ANY
    v_pages_hbm,  # [num_pages, ps, Hkv*D] int8
    out_ref,      # [1, Hq, Hkv*D] VMEM
    # scratch
    kbuf,     # [2, CP*ps, Hkv*D] int8
    vbuf,     # [2, CP*ps, Hkv*D] int8
    ksem,
    vsem,
    m_ref,
    l_ref,
    acc_ref,
    *,
    page_size: int,
    pages_per_chunk: int,
    scale: float,
):
    """Int8-KV variant of _decode_kernel: pages DMA as int8 (HALF the HBM
    traffic of the bf16 kernel — the whole point), and the per-slot
    dequant scales fold into the math instead of materializing dequantized
    K/V: score[h,j] = (qx . k_q^T)[h,j] * s_k[j] and the PV product uses
    pexp * s_v — exactly runtime/kv_cache.py's `q * s` dequant, fused.
    The scales arrive pre-gathered in LOGICAL window order (chunk-major
    [NC, chunk] so chunk c is one static-shape sublane row — Mosaic-safe
    dynamic indexing, no in-kernel reshape across tiles)."""
    b = pl.program_id(0)
    ps, cp = page_size, pages_per_chunk
    chunk = cp * ps
    n_valid = seq_lens_ref[b] + 1
    n_pages = pl.cdiv(n_valid, ps)
    n_chunks = pl.cdiv(n_pages, cp)

    def issue(c, slot):
        for j in range(cp):
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_table_ref[b, c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).start()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).start()

    def wait(c, slot):
        for j in range(cp):
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_table_ref[b, c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).wait()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).wait()

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    issue(0, 0)

    def body(c, carry):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            issue(c + 1, jax.lax.rem(c + 1, 2))

        wait(c, slot)

        remaining = n_valid - c * chunk
        local = jax.lax.broadcasted_iota(jnp.int32, (1, chunk), dimension=1)
        slot_mask = local < remaining
        local_col = jax.lax.broadcasted_iota(jnp.int32, (chunk, 1), dimension=0)
        col_mask = local_col < remaining

        ksw = ksw_ref[0, c, :][None, :]  # [1, chunk] f32
        vsw = vsw_ref[0, c, :][None, :]
        kc = kbuf[slot].astype(jnp.float32)  # int8 -> f32
        # never-DMA'd rows hold stale int8 garbage, but int8 cannot be
        # NaN/inf: K garbage is masked to NEG_INF scores, V garbage is
        # zeroed like the dense kernel
        vc = jnp.where(col_mask, vbuf[slot].astype(jnp.float32), 0.0)
        qx = q_ref[0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                qx, kc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        ) * ksw  # fused per-slot k dequant
        s = jnp.where(slot_mask, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(slot_mask, pexp, 0.0)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp * vsw, vc,  # fused per-slot v dequant
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new
        return carry

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[...], 1e-30)
    out_ref[0, :, :] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "pages_per_chunk", "scale", "interpret"),
)
def paged_decode_attention_int8(
    q: jnp.ndarray,            # [B, Hq, D]
    k_q: jnp.ndarray,          # [TOTAL_SLOTS, Hkv*D] int8 rows
    k_s: jnp.ndarray,          # [TOTAL_SLOTS, 1] f32 per-slot scales
    v_q: jnp.ndarray,
    v_s: jnp.ndarray,
    page_table: jnp.ndarray,   # [B, P]
    seq_lens: jnp.ndarray,     # [B]
    *,
    page_size: int,
    pages_per_chunk: int = 8,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Decode attention straight off the int8-quantized paged pool
    (runtime/kv_cache.py kv_quantize="int8": QTensor rows + per-slot
    scales).  The kernel streams HALF the KV bytes of the bf16 kernel;
    the scales ride as an XLA page-granular pre-gather (4 B/slot — noise
    next to the row bytes) shaped chunk-major for Mosaic-safe indexing.
    Same contract as paged_decode_attention otherwise."""
    B, Hq, D = q.shape
    HD = k_q.shape[1]
    Hkv = HD // D
    G = Hq // Hkv
    P = page_table.shape[1]
    if scale is None:
        scale = D**-0.5
    cp = min(pages_per_chunk, P)
    nc = -(-P // cp)  # chunks per window
    k_pages = k_q.reshape(-1, page_size, HD)
    v_pages = v_q.reshape(-1, page_size, HD)

    def window_scales(s):
        # [SLOTS, 1] -> [B, NC, chunk] in logical window order: page-
        # granular gather (16x fewer descriptors than per-slot), pages
        # padded up to nc*cp so every chunk row is full width
        sp = s.reshape(-1, page_size)[page_table]      # [B, P, ps]
        pad = nc * cp - P
        if pad:
            sp = jnp.pad(sp, ((0, 0), (0, pad), (0, 0)))
        return sp.reshape(B, nc, cp * page_size).astype(jnp.float32)

    ksw = window_scales(k_s)
    vsw = window_scales(v_s)

    kv_of_q = jnp.repeat(jnp.arange(Hkv), G)
    qx = jnp.zeros((B, Hq, Hkv, D), q.dtype)
    qx = qx.at[:, jnp.arange(Hq), kv_of_q].set(q)
    qx = qx.reshape(B, Hq, HD)

    chunk = cp * page_size
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, HD), lambda b, pt, sl: (b, 0, 0)),
            pl.BlockSpec((1, nc, chunk), lambda b, pt, sl: (b, 0, 0)),
            pl.BlockSpec((1, nc, chunk), lambda b, pt, sl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hq, HD), lambda b, pt, sl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, HD), k_q.dtype),
            pltpu.VMEM((2, chunk, HD), v_q.dtype),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.VMEM((Hq, 1), jnp.float32),
            pltpu.VMEM((Hq, 1), jnp.float32),
            pltpu.VMEM((Hq, HD), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel_int8,
        page_size=page_size,
        pages_per_chunk=cp,
        scale=scale,
    )
    out_wide = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, HD), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, qx, ksw, vsw, k_pages, v_pages)
    return out_wide.reshape(B, Hq, Hkv, D)[:, jnp.arange(Hq), kv_of_q]


def paged_decode_attention_int8_sharded(
    mesh,
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_s: jnp.ndarray,
    v_q: jnp.ndarray,
    v_s: jnp.ndarray,
    page_table: jnp.ndarray,
    seq_lens: jnp.ndarray,
    *,
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Int8 kernel on a tp(/tq) mesh — same layout contract as
    paged_decode_attention_sharded; the per-slot scales are head-agnostic
    ([SLOTS, 1]) and ride replicated."""
    from jax.sharding import PartitionSpec as P

    q_ax = ("tp", "tq") if mesh.shape.get("tq", 1) > 1 else "tp"
    fn = shard_map(
        functools.partial(
            paged_decode_attention_int8,
            page_size=page_size, interpret=interpret,
        ),
        mesh=mesh,
        in_specs=(P(None, q_ax, None),
                  P(None, "tp"), P(None, None),
                  P(None, "tp"), P(None, None),
                  P(None, None), P(None)),
        out_specs=P(None, q_ax, None),
        check_vma=False,
    )
    return fn(q, k_q, k_s, v_q, v_s, page_table, seq_lens)
