"""Paged-attention decode kernel.

One decode step attends each sequence's KV window, which lives scattered
across physical pages of the shared pool (runtime/kv_cache.py).  The XLA
reference path materializes the whole [B, C, Hkv, D] window per layer via a
gather — it reads the *configured* window regardless of how long each
sequence actually is, and round-trips the gathered copy through HBM.  This
kernel walks each sequence's page list directly:

* grid = (B,): one program per sequence.  The page table and sequence
  lengths ride in as **scalar-prefetch** arguments so the kernel can
  dereference physical page ids at runtime.
* the kernel iterates only over the sequence's *valid* pages — a dynamic
  `fori_loop` over chunks of `pages_per_chunk` pages, each chunk landed in
  VMEM by manually issued per-page async DMAs, double-buffered so chunk
  c+1's copies overlap chunk c's compute.  A sequence 300 tokens into an
  8k window reads 300 tokens' worth of KV, not 8k.
* online softmax (m, l, acc) in VMEM scratch across chunks.  GQA is an
  unrolled per-kv-head loop over query groups — no repeat_kv
  materialization.

Layout contract: the pool stores each slot's row as Hkv*D merged lanes
([TOTAL_SLOTS, Hkv*D]) — Mosaic requires DMA slices to be lane-tile (128)
aligned, so per-head layouts with D=64 cannot be page-DMA'd; the merged row
(512 lanes for 8x64) can.  Mosaic also cannot unfold merged lanes back to
heads in-kernel, so GQA is expressed *algebraically*: the caller expands q
block-diagonally to [Hq, Hkv*D] (zeros outside each query head's own
kv-head lane block), QK^T over merged rows then contracts exactly the right
D lanes per head in one full-width MXU matmul, and the PV product yields
[Hq, Hkv*D] from which the caller slices each row's own kv-head block.

Numerics ground truth: ops.attention.causal_attention (tests compare both
paths on random page layouts).  f32 accumulation throughout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch
    page_table_ref,  # [B, P] i32
    seq_lens_ref,    # [B] i32
    # inputs
    q_ref,        # [1, Hq, Hkv*D] VMEM block — block-diagonal expanded q
    k_pages_hbm,  # [num_pages, ps, Hkv*D] in HBM/ANY
    v_pages_hbm,  # [num_pages, ps, Hkv*D] in HBM/ANY
    out_ref,      # [1, Hq, Hkv*D] VMEM block — caller slices per-head lanes
    # scratch
    kbuf,     # [2, CP*ps, Hkv*D] pool dtype
    vbuf,     # [2, CP*ps, Hkv*D]
    ksem,     # DMA sems [2, CP]
    vsem,     # DMA sems [2, CP]
    m_ref,    # [Hq, 1] f32 running max
    l_ref,    # [Hq, 1] f32 running denominator
    acc_ref,  # [Hq, Hkv*D] f32 running numerator
    *,
    page_size: int,
    pages_per_chunk: int,
    scale: float,
):
    b = pl.program_id(0)
    ps, cp = page_size, pages_per_chunk
    chunk = cp * ps
    # query position is seq_len; it attends positions <= seq_len
    n_valid = seq_lens_ref[b] + 1
    n_pages = pl.cdiv(n_valid, ps)
    n_chunks = pl.cdiv(n_pages, cp)

    def issue(c, slot):
        for j in range(cp):  # static unroll; per-page scattered DMA
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_table_ref[b, c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).start()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).start()

    def wait(c, slot):
        for j in range(cp):
            @pl.when(c * cp + j < n_pages)
            def _():
                page = page_table_ref[b, c * cp + j]
                pltpu.make_async_copy(
                    k_pages_hbm.at[page],
                    kbuf.at[slot, pl.ds(j * ps, ps)],
                    ksem.at[slot, j],
                ).wait()
                pltpu.make_async_copy(
                    v_pages_hbm.at[page],
                    vbuf.at[slot, pl.ds(j * ps, ps)],
                    vsem.at[slot, j],
                ).wait()

    m_ref[...] = jnp.full_like(m_ref, NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    issue(0, 0)

    def body(c, carry):
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            issue(c + 1, jax.lax.rem(c + 1, 2))

        wait(c, slot)

        # mask: local slot index within the chunk vs remaining valid slots
        remaining = n_valid - c * chunk
        local = jax.lax.broadcasted_iota(jnp.int32, (1, chunk), dimension=1)
        slot_mask = local < remaining  # [1, chunk]
        local_col = jax.lax.broadcasted_iota(jnp.int32, (chunk, 1), dimension=0)
        col_mask = local_col < remaining  # [chunk, 1]

        # Merged-lane compute: q arrives pre-expanded block-diagonally
        # ([Hq, Hkv*D], zeros outside each query head's own kv-head lane
        # block), so QK^T over the full merged row contracts exactly each
        # head's D lanes — one MXU matmul for all heads, no in-kernel
        # reshape (Mosaic cannot unfold merged lanes).  Rows past the valid
        # range were never DMA'd; zero V before the PV matmul — a NaN there
        # would poison the accumulator even under zero probability weight
        # (0 * NaN = NaN).  K needs no masking: its scores are overwritten
        # by the NEG_INF mask.
        kc = kbuf[slot].astype(jnp.float32)  # [chunk, HD]
        vc = jnp.where(col_mask, vbuf[slot].astype(jnp.float32), 0.0)
        qx = q_ref[0].astype(jnp.float32)  # [Hq, HD] block-diagonal
        s = (
            jax.lax.dot_general(
                qx, kc,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Hq, chunk]
        s = jnp.where(slot_mask, s, NEG_INF)

        m_prev = m_ref[...]  # [Hq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(slot_mask, pexp, 0.0)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
        # [Hq, HD]: each row holds every kv head's weighted V; the caller
        # slices out the row's own kv-head lane block.
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, vc,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new
        return carry

    jax.lax.fori_loop(0, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[...], 1e-30)
    out_ref[0, :, :] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "pages_per_chunk", "scale", "interpret"),
)
def paged_decode_attention(
    q: jnp.ndarray,            # [B, Hq, D] — one query token per sequence
    k_pool: jnp.ndarray,       # [TOTAL_SLOTS, Hkv*D] merged-lane pool
    v_pool: jnp.ndarray,       # [TOTAL_SLOTS, Hkv*D]
    page_table: jnp.ndarray,   # [B, P] i32 physical page ids
    seq_lens: jnp.ndarray,     # [B] i32 tokens already cached (query pos)
    *,
    page_size: int,
    pages_per_chunk: int = 8,
    scale: float | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Decode-step attention straight off the paged KV pool.

    Returns [B, Hq, D] in q.dtype.  Inactive batch lanes (whose table rows
    point at the trash page) produce garbage rows that the engine discards —
    same contract as the XLA gather path.
    """
    B, Hq, D = q.shape
    HD = k_pool.shape[1]
    Hkv = HD // D
    G = Hq // Hkv
    P = page_table.shape[1]
    if scale is None:
        scale = D**-0.5
    cp = min(pages_per_chunk, P)
    k_pages = k_pool.reshape(-1, page_size, HD)
    v_pages = v_pool.reshape(-1, page_size, HD)

    # Block-diagonal query expansion (see module docstring): qx[b, qh] has
    # q[b, qh] in its own kv head's D-lane block and zeros elsewhere.
    kv_of_q = jnp.repeat(jnp.arange(Hkv), G)  # [Hq]
    qx = jnp.zeros((B, Hq, Hkv, D), q.dtype)
    qx = qx.at[:, jnp.arange(Hq), kv_of_q].set(q)
    qx = qx.reshape(B, Hq, HD)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Hq, HD), lambda b, pt, sl: (b, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, Hq, HD), lambda b, pt, sl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, cp * page_size, HD), k_pool.dtype),
            pltpu.VMEM((2, cp * page_size, HD), v_pool.dtype),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.SemaphoreType.DMA((2, cp)),
            pltpu.VMEM((Hq, 1), jnp.float32),
            pltpu.VMEM((Hq, 1), jnp.float32),
            pltpu.VMEM((Hq, HD), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel,
        page_size=page_size,
        pages_per_chunk=cp,
        scale=scale,
    )
    out_wide = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hq, HD), q.dtype),
        interpret=interpret,
    )(page_table, seq_lens, qx, k_pages, v_pages)
    # each query row's result lives in its own kv head's lane block
    return out_wide.reshape(B, Hq, Hkv, D)[:, jnp.arange(Hq), kv_of_q]
