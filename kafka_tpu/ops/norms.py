"""Normalization ops.

RMSNorm is computed in float32 regardless of activation dtype (bf16 inputs
lose too much precision in the mean-square reduction on the MXU-adjacent
vector units), then cast back — the standard TPU recipe.
"""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Llama-style RMSNorm: x * rsqrt(mean(x^2) + eps) * weight."""
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(orig_dtype)
