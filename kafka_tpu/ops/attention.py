"""Attention ops — XLA reference implementations.

These einsum formulations are the portable baseline: they run on CPU (tests)
and TPU, and XLA already fuses mask+softmax+matmul chains well on the MXU.
The Pallas kernels in ops/pallas/ override them on TPU for the flash
(prefill) and paged (decode) paths; this module is the numerics ground truth
those kernels are tested against.

Layout convention throughout the framework: activations are
[batch, seq, heads, head_dim] ("BSHD") — the layout that shards naturally
over a ("dp", "tp") mesh with heads on "tp".
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import jax


NEG_INF = -1e30  # large-negative mask value; -inf breaks softmax when a row is fully masked


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Expand KV heads for GQA: [B, S, Hkv, D] -> [B, S, Hkv*n_rep, D]."""
    if n_rep == 1:
        return x
    b, s, h, d = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, h, n_rep, d))
    return x.reshape(b, s, h * n_rep, d)


def causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    kv_valid: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Masked scaled-dot-product attention with GQA.

    q: [B, Sq, Hq, D]   k/v: [B, Skv, Hkv, D]
    q_positions: [B, Sq] absolute position of each query token
    kv_positions: [B, Skv] absolute position of each kv slot
    kv_valid: [B, Skv] bool — False for empty cache slots/padding
    Causality: a query at position p attends kv slots with position <= p.
    Works for prefill (Sq == Skv), chunked prefill, and decode (Sq == 1)
    against a longer cache.
    """
    b, sq, hq, d = q.shape
    hkv = k.shape[2]
    n_rep = hq // hkv
    if scale is None:
        scale = d**-0.5

    # Grouped GQA formulation: fold the repeat factor into the einsum batch
    # dims instead of materializing n_rep copies of K/V (repeat_kv would
    # stream the whole KV window through HBM n_rep times per layer).  The
    # matmuls take bf16 inputs with f32 accumulation (the MXU-native mode);
    # only the [.., Sq, Skv] score tensor is ever f32.
    qg = q.reshape(b, sq, hkv, n_rep, d)
    # [B, Hkv, G, Sq, Skv]
    logits = (
        jnp.einsum("bqhgd,bkhd->bhgqk", qg, k, preferred_element_type=jnp.float32)
        * scale
    )

    mask = q_positions[:, None, None, :, None] >= kv_positions[:, None, None, None, :]
    if kv_valid is not None:
        mask = mask & kv_valid[:, None, None, None, :]
    logits = jnp.where(mask, logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, sq, hq, d).astype(q.dtype)
