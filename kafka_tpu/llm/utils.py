"""Provider-level message utilities.

Parity targets from the reference's provider utils (src/llm/utils.py):
model→provider routing heuristic (:11-29) and image pruning to the newest
N images (:85-130).  Message normalization for Gemini-style providers
(:32-82) is irrelevant to a local engine and intentionally absent; the
related opaque-field passthrough (thought_signature, portkey.py:282-287)
IS preserved — unknown top-level message keys round-trip through
core.types.Message.extra and the thread store.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List

MAX_IMAGES_DEFAULT = 19  # reference cap: src/llm/portkey.py:276


def infer_provider_from_model(model: str) -> str:
    """Heuristic model-name → provider-family routing.

    Kept for wire compatibility with clients that pass foreign model ids;
    anything unrecognized is served by the local TPU engine.
    """
    m = (model or "").lower()
    if m.startswith(("gpt-", "o1", "o3", "o4", "chatgpt")):
        return "openai"
    if m.startswith("claude"):
        return "anthropic"
    if m.startswith("gemini"):
        return "google"
    if m.startswith(("mistral", "mixtral", "ministral")):
        return "mistral"
    return "tpu"


def _is_image_part(part: Any) -> bool:
    return isinstance(part, dict) and part.get("type") in ("image_url", "image")


def count_images(messages: List[Dict[str, Any]]) -> int:
    n = 0
    for m in messages:
        c = m.get("content")
        if isinstance(c, list):
            n += sum(1 for p in c if _is_image_part(p))
    return n


def prune_images(
    messages: List[Dict[str, Any]], max_images: int = MAX_IMAGES_DEFAULT
) -> List[Dict[str, Any]]:
    """Keep only the newest `max_images` images across the conversation.

    Older images are replaced with a short text placeholder so message
    structure (and tool-call pairing) is preserved.  Returns a deep-ish copy
    when pruning happens; returns the input list unchanged otherwise.
    """
    total = count_images(messages)
    if total <= max_images:
        return messages
    to_drop = total - max_images
    out: List[Dict[str, Any]] = []
    dropped = 0
    for m in messages:
        c = m.get("content")
        if dropped < to_drop and isinstance(c, list) and any(
            _is_image_part(p) for p in c
        ):
            m = copy.copy(m)
            new_parts: List[Any] = []
            for p in c:
                if dropped < to_drop and _is_image_part(p):
                    new_parts.append(
                        {"type": "text", "text": "[image removed to fit context]"}
                    )
                    dropped += 1
                else:
                    new_parts.append(p)
            m["content"] = new_parts
        out.append(m)
    return out
