"""`TPULLMProvider` — the LLMProvider served by the local TPU engine.

This is the component that replaces the reference's remote gateway provider
(reference: src/llm/portkey.py:62-701, an HTTPS proxy to provider GPUs).
Requests go straight into the continuous-batching engine via the dispatch
thread (llm/worker.py) and tokens stream back per-request with no network
in the loop.

Differences from the reference, by design:

* **Pre-flight context checking.** The engine tokenizes locally, so context
  overflow raises a typed `ContextLengthError` *before* any compute — the
  reference could only string-match a remote 400 after the fact
  (src/llm/context_compaction/base.py:10-65).
* **True per-token streaming.** Chunks are yielded as the decode loop emits
  tokens (the reference buffered whole completions, src/agents/base.py:231).
* **Real usage accounting** on every path, including streaming.
* **Native tool-call decoding.** Generated text that opens a JSON object or
  array is buffered and parsed into OpenAI tool_calls; plain text streams
  through immediately.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence

from ..core.types import (
    CompletionResponse,
    ContextLengthError,
    LLMProviderError,
    ServerOverloadedError,
    StreamChunk,
    UnsupportedContentError,
    Usage,
    new_completion_id,
)
from ..models.config import ModelConfig
from ..models.tokenizer import BaseTokenizer, parse_tool_call_text
from ..runtime.engine import GenRequest, InferenceEngine, TokenEvent
from ..runtime.tracing import current as current_trace
from .base import LLMProvider, MessageLike, to_message_dicts
from .constrained import grammar_ondevice_enabled as _grammar_ondevice_enabled
from .utils import count_images
from .worker import EngineWorker

logger = logging.getLogger("kafka_tpu.llm.tpu")

# resize_dp `roles` default: KEEP the current role-pool spec (re-derived
# for the new dp by the router, today's behavior).  Distinct from None,
# which explicitly dissolves the pools back to colocated serving.
_ROLES_KEEP = object()


def _torn_items(d) -> list:
    """Snapshot a dict the engine thread mutates concurrently.

    list(dict.items()) can raise "dictionary changed size" mid-copy —
    retry (the runtime/metrics.py policy); torn reads are fine (a request
    finishing during the copy no longer needs attention)."""
    for _ in range(8):
        try:
            return list(d.items())
        except RuntimeError:
            continue
    return []


class IncrementalDetokenizer:
    """Streams token ids to text without re-decoding the whole output.

    Standard two-offset scheme: hold back the tail while it decodes to an
    incomplete UTF-8 sequence (replacement char), emit once it stabilizes.
    """

    def __init__(self, tokenizer: BaseTokenizer):
        self._tok = tokenizer
        self._ids: List[int] = []
        # decode window: [prefix, read) is already-emitted context kept so
        # tokenizers whose decode depends on neighbors (sentencepiece space
        # handling) produce stable text; [read, end) is pending.
        self._prefix = 0
        self._read = 0

    def push(self, token_id: int) -> str:
        self._ids.append(token_id)
        emitted = self._tok.decode(self._ids[self._prefix : self._read])
        full = self._tok.decode(self._ids[self._prefix :])
        if len(full) > len(emitted) and not full.endswith("�"):
            delta = full[len(emitted) :]
            self._prefix = self._read
            self._read = len(self._ids)
            return delta
        return ""

    def flush(self) -> str:
        """Emit whatever remains (end of stream), replacement chars and all."""
        emitted = self._tok.decode(self._ids[self._prefix : self._read])
        full = self._tok.decode(self._ids[self._prefix :])
        self._read = self._prefix = len(self._ids)
        return full[len(emitted) :] if len(full) > len(emitted) else ""

    @property
    def ids(self) -> List[int]:
        return self._ids


class TPULLMProvider(LLMProvider):
    """Serves chat completions from the in-process TPU engine."""

    provider_name = "tpu"
    # Agent-native scheduling (ISSUE 20): callers that own an agent loop
    # feature-detect these before passing background=True or firing
    # note_tool_return — OpenAI-shaped providers have neither.
    supports_background = True

    def __init__(
        self,
        engine: InferenceEngine,
        tokenizer: BaseTokenizer,
        model_name: str = "llama",
        worker: Optional[EngineWorker] = None,
        vision_params: Any = None,
    ):
        self.engine = engine
        self.tokenizer = tokenizer
        self.model_name = model_name
        self.worker = worker or EngineWorker(engine)
        self.worker.start()
        self._counter = itertools.count()
        # topology-rebuild coordination: one resize at a time; while held
        # (or waited on) the admission gate turns new traffic away, which
        # is what makes the resize drain work a finite set and converge
        self._resize_lock = asyncio.Lock()
        # True while a CANCELLED rebuild thread still runs: its completion
        # callback owns the worker resume, and the orphaned future below
        # gates the next resize (see _resize_locked)
        self._rebuild_owns_resume = False
        self._orphan_rebuild: Optional[Any] = None
        # the autoscaler control loop (runtime/autoscaler.py) attaches
        # itself here; /admin/signals v4 echoes its state when present
        self.autoscaler: Optional[Any] = None
        # Vision tower params (models/vision.py) — present iff the model
        # config has a VisionConfig; image requests 400 otherwise.
        self.vision_params = vision_params
        self._encode_images = None
        if vision_params is not None and self.model_cfg.vision is not None:
            import functools as _ft

            import jax as _jax

            from ..models.vision import encode_images as _enc

            # the sentinel scheme requires a tokenizer where NUL is one
            # token that round-trips (the byte tokenizer's id 0); a
            # subword checkpoint tokenizer must bring its own native
            # image token instead of silently mis-splitting the sentinel
            nul = tokenizer.encode("\x00")
            if len(nul) != 1 or tokenizer.decode(nul) != "\x00":
                raise ValueError(
                    "vision serving requires a tokenizer with a "
                    "single-token NUL sentinel (byte-level); this "
                    f"tokenizer encodes NUL as {nul!r}"
                )
            self._encode_images = _jax.jit(
                _ft.partial(_enc, vision_params, self.model_cfg.vision)
            )
        # pre-build the constrained-decoding vocab index off the event loop
        # so the first tool_choice-constrained request doesn't stall serving
        from .constrained import TokenIndex

        TokenIndex.warm(tokenizer)

    # ------------------------------------------------------------------

    @property
    def model_cfg(self) -> ModelConfig:
        return self.engine.cfg

    def count_prompt_tokens(
        self,
        messages: Sequence[MessageLike],
        tools: Optional[List[Dict[str, Any]]] = None,
    ) -> int:
        """Token count of the rendered prompt (compaction pre-flight).

        Vision prompts are priced with their expansion: each surviving
        image costs num_patches placeholder tokens (its 1-token sentinel
        is replaced), after the same newest-N pruning serving applies."""
        dicts = to_message_dicts(messages)
        # gate on the SERVING capability (encode fn), not just the config:
        # pricing must agree with what stream_completion will accept
        if self._encode_images is not None and count_images(dicts):
            from .images import sentinelize_images
            from .utils import prune_images

            dicts, parts = sentinelize_images(prune_images(dicts))
            n = len(self.tokenizer.encode_chat(dicts, tools=tools))
            return n + len(parts) * (self.model_cfg.vision.num_patches - 1)
        return len(self.tokenizer.encode_chat(dicts, tools=tools))

    @property
    def max_prompt_tokens(self) -> int:
        """Largest admissible prompt (engine window, minus 1 for decode)."""
        return min(self.engine.ecfg.max_window, self.model_cfg.max_context) - 1

    def note_tool_return(self, prefix_key: Optional[str]) -> None:
        """The thread's tool finished: fire its expected-return hint.

        Called by the agent loop (or the sandbox SSE terminal event) the
        moment tool execution completes — BEFORE the follow-up turn is
        even composed — so a demote-in-linger cancels and a demoted
        thread's wake prefetch overlaps the tool's tail.  Engine-thread
        op via the worker inbox; no-op with KAFKA_TPU_AGENT_DEMOTE
        unset."""
        self.worker.note_tool_return(prefix_key)

    # -- lifecycle hardening (server/app.py admission gate + drain) ------

    def _replicas(self):
        """The engine as a replica list (DataParallelEngines unwraps to
        its .engines; a single engine is its own one-element set)."""
        return getattr(self.engine, "engines", [self.engine])

    def admission_check(self) -> Optional[float]:
        """None = admit; else a Retry-After estimate in seconds.

        Reads the engine thread's queue length without synchronization —
        torn reads only make the gate a step stale, and the engine-side
        submit bound (EngineConfig.max_waiting) is the authoritative
        backstop for the race.  With DP replicas, admit while ANY replica
        has room (the router picks per-thread).
        """
        if self._resize_lock.locked():
            # topology rebuild in flight (or queued): turn new traffic
            # away (429 + Retry-After) so the resize drain works a
            # FINITE set
            return 5.0
        limit = self.engine.ecfg.max_waiting
        if limit <= 0:
            return None
        replicas = self._replicas()
        # a quarantined replica's empty queue is not capacity — the
        # router will not place anything there; gate on ROUTABLE
        # replicas or overload 429s are replaced by admission churn
        health = getattr(self.engine, "health", None)
        if health is not None:
            routable = [e for e, h in zip(replicas, health) if h.routable]
            replicas = routable or replicas
        if any(len(e.waiting) < limit for e in replicas):
            return None
        return min(e.retry_after_estimate() for e in replicas)

    def record_rejection(self) -> None:
        """Count a gate-level HTTP 429 in requests.rejected (the engine
        backstop counts its own; without this, sustained overload — where
        the gate catches nearly everything — would show ~0 rejections).
        A rejection is also an SLO miss (metrics.record_rejected), so the
        attainment gauges see shed load, and a flight-recorder "reject"
        cause (drained into the next ring record), so an overload
        burst's postmortem shows the shed traffic.  Cross-thread int
        increment: GIL-atomic enough for a counter."""
        replica = self._replicas()[0]
        replica.metrics.record_rejected()
        flight = getattr(replica, "flight", None)
        if flight is not None:
            flight.note_gate_reject()

    def signals(self) -> Dict[str, Any]:
        """One coherent autoscaler-input snapshot (GET /admin/signals,
        ISSUE 10).  This is the INPUT CONTRACT for the coming resize
        control loop — the fields below are stable:

        * ``queue``: dp-wide waiting depth, peak since last snapshot, and
          the 60s depth slope (``trend_per_s`` > 0 = demand outrunning
          capacity).
        * ``batch``: decode-slot occupancy (mean busy slots per step /
          max_batch), active lanes, configured max_batch x dp.
        * ``slo``: window attainment (1m/5m), the configured targets, and
          goodput (tokens from SLO-met requests) — scale up when
          attainment_1m sags under the target with a rising queue; scale
          down when attainment holds at 1.0 with idle occupancy.
        * ``utilization``: per-dispatch-kind MFU / HBM-bandwidth
          utilization against the chip roofline (since-boot + 1m) — how
          close each replica runs to the hardware, i.e. whether more
          replicas or bigger batches is the right lever.
        * ``replicas``: per-replica health state (quarantined replicas
          are capacity the router cannot use), load, KV-page headroom,
          and utilization.
        * ``pools`` (version 3, ISSUE 12): one entry per role pool —
          role ("prefill" / "decode", or "colocated" when
          KAFKA_TPU_DP_ROLES is unset), replica ids, queue depth, batch
          occupancy, and per-kind MFU / HBM-BW utilization — so the
          autoscaler can size the prefill pool (compute-bound) and the
          decode pool (bandwidth-bound) INDEPENDENTLY: grow prefill on
          prefill-pool queue growth with high prefill MFU, grow decode
          on decode-pool attainment collapse with high HBM-BW
          utilization.  ``disagg`` carries the router's ship counters
          (runs/pages/bytes, failures, fallbacks) when pools are
          configured, else null.
        * ``anomalies`` (version 2, ISSUE 11): the flight recorder's
          step-cadence detector state — edge-triggered firing counters
          plus the CURRENTLY-ACTIVE list (queue stall, fetch-pipeline
          starvation, MFU collapse, prefill convoy), each active entry
          naming the replica it fires on.  This is the "something is
          wrong, don't scale on stale math" input: while any anomaly is
          active the utilization/attainment numbers describe a sick
          replica, and a controller must hold rather than resize on
          them.  The ``utilization`` section also carries the measured
          dispatch timing (``measured_busy_s``/``modeled_busy_s``/
          ``model_skew``) calibrating the modeled MFU/HBM-BW figures.
        * ``autoscaler`` (version 4, ISSUE 13): the in-process control
          loop's state when one runs (mode, degradation-ladder rung,
          resize cooldowns, last decision) — null when
          KAFKA_TPU_AUTOSCALE is off.  Version 4 also adds
          ``slo.window_1m_requests`` (how many MET/MISSED verdicts back
          the 1m attainment gauge, so a reader can tell "1.0 because
          everything met" from "1.0 because nothing finished").
        * ``object_tier`` (version 5, ISSUE 14): the shared object
          store's occupancy, cross-host dedupe ratio, and sleep-manifest
          wake counts — with the tier mounted, scale-in is
          drain-then-shrink (warm state survives the removed replica),
          so a controller can shrink more aggressively.  Null when
          KAFKA_TPU_KV_OBJECT_DIR is unset.  Version 6 (ISSUE 17) adds
          store HEALTH to the section: ``breaker_state``
          ("closed"/"half_open"/"open" — the dp max, so any replica's
          open breaker surfaces), ``breaker_opens``,
          ``store_available`` (False = the store is fast-failing and
          the pre-scale-in drain will be SKIPPED: shrink decisions
          should assume dormant threads re-prefill), and the
          retry/timeout/error/negative-probe counters behind it.
        * ``compiles`` (version 7, ISSUE 18): the compile observatory's
          ring summary — compiles_total, seconds, cache hit/miss/off
          split, current phase, and ``storm_active``: True means XLA is
          recompiling under live traffic (a shape regression or cache
          wipe) and EVERY resize must hold — latency numbers during a
          storm measure the compiler, not capacity.  Null when
          KAFKA_TPU_COMPILE_RING=0.
        * zero-host-copy movement (version 8, ISSUE 19):
          ``object_tier.prefetch`` carries the wake-prefetch
          hits/wasted/bytes/inflight counters (all zeros when
          KAFKA_TPU_WAKE_PREFETCH_MB is unset), and ``disagg`` gains the
          ship-transport split (``disagg_ship_host_runs`` /
          ``disagg_ship_device_runs``) plus the host-staging peak gauge
          (``disagg_ship_staging_bytes``).
        * ``memory`` (version 7, ISSUE 18): measured HBM against the
          startup MemoryPlan — worst-case ``headroom_bytes`` (min over
          replicas), ``plan_skew`` (measured bytes_in_use / planned
          total; > 1 = the plan under-charges, so size scale-ups from
          the device numbers, not the plan), ``pressure`` (headroom
          under the watermark — the degradation ladder's shed input),
          plus the per-replica rows.  Null before the first poll.
        * ``agent`` (version 9, ISSUE 20): agent-native scheduling —
          ``awaiting_threads`` / ``awaiting_bytes`` (threads mid
          tool-call gap and the demoted KV bytes parked for them in
          lower tiers), the expected-return hint hit/miss split, gap
          demotion counters, and the background-class queue depth /
          admit / chunk / yield counters.  CONTRACT NOTE for
          controllers: awaiting-tool threads are NOT load — their KV
          sits in host/disk/object tiers and they occupy no decode
          slot, so they must not count toward queue depth or occupancy
          when sizing the fleet (scale on ``queue`` and ``batch`` as
          before; ``awaiting_threads`` only predicts FUTURE wake
          traffic).  All zeros when KAFKA_TPU_AGENT_DEMOTE is unset
          and no background-class work ran.

        Everything is read torn-tolerantly from the engine thread's
        single-writer metrics; no locks, safe at scrape frequency.
        """
        engine = self.engine
        # reset_peak=False: the ~1 Hz signal poll must not consume the
        # /metrics scraper's peak-since-last-snapshot window
        snap = engine.metrics.snapshot(engine, reset_peak=False)
        replicas = self._replicas()
        health = getattr(engine, "health", None)
        occupancy = snap.get("decode", {}).get("batch_occupancy", 0.0)
        max_batch = engine.ecfg.max_batch
        per_replica: List[Dict[str, Any]] = []
        rep_snaps = snap.get("replicas")
        for i, e in enumerate(replicas):
            rs = (rep_snaps[i] if rep_snaps and i < len(rep_snaps)
                  else snap)
            util = rs.get("utilization") or {}
            per_replica.append({
                "replica": i,
                "state": health[i].state if health else "healthy",
                "active": e.num_active,
                "waiting": len(e.waiting),
                "parked": len(e.parked),
                "pages_free": e.pool.free_pages,
                "pages_total": e.pool.num_pages,
                "batch_occupancy": rs.get("decode", {}).get(
                    "batch_occupancy", 0.0
                ),
                "anomalies_active": (rs.get("anomalies") or {}).get(
                    "anomalies_active", 0
                ),
                "utilization": {
                    kind: {
                        "mfu": util.get(kind, {}).get("mfu", 0.0),
                        "mfu_1m": util.get(kind, {}).get("mfu_1m", 0.0),
                        "hbm_bw_util": util.get(kind, {}).get(
                            "hbm_bw_util", 0.0
                        ),
                        "hbm_bw_util_1m": util.get(kind, {}).get(
                            "hbm_bw_util_1m", 0.0
                        ),
                        # measured/modeled calibration (ISSUE 11): >1 =
                        # this replica runs slower than the cost model
                        # assumes, so its MFU figures read high
                        "model_skew": util.get(kind, {}).get(
                            "model_skew", 0.0
                        ),
                    }
                    for kind in ("prefill", "decode", "verify")
                },
            })
        # anomalies: the aggregate section already attributes active
        # entries to replicas (dp); a single engine's lacks the field —
        # stamp replica 0 so the contract shape is dp-independent
        anomalies = dict(snap.get("anomalies") or {})
        if anomalies.get("active"):
            anomalies["active"] = [
                {**a, "replica": a.get("replica", 0)}
                for a in anomalies["active"]
            ]
        # Per-pool section (version 3, ISSUE 12): the aggregate snapshot
        # carries it when role pools are configured; otherwise the whole
        # fleet is one "colocated" pool so the contract shape is
        # role-independent.
        disagg = snap.get("disagg") or {}
        if disagg.get("pools"):
            pools = disagg["pools"]
        else:
            pools = [{
                "role": "colocated",
                "replicas": list(range(len(replicas))),
                "queue_depth": sum(len(e.waiting) for e in replicas),
                "active": engine.num_active,
                "parked": sum(len(e.parked) for e in replicas),
                "batch_occupancy": occupancy,
                "utilization": {
                    kind: {
                        k: (snap.get("utilization") or {}).get(
                            kind, {}
                        ).get(k, 0.0)
                        for k in ("mfu", "mfu_1m", "hbm_bw_util",
                                  "hbm_bw_util_1m")
                    }
                    for kind in ("prefill", "decode", "verify")
                },
            }]
        # SLO section: the raw window dicts stay internal to /metrics,
        # but the controller needs to know whether the 1m attainment
        # gauge rests on enough verdicts to act on — version 4 exports
        # that one scalar (met + missed in the 60s window)
        slo_src = snap.get("slo") or {}
        slo_out = {
            k: v for k, v in slo_src.items()
            if not k.startswith("window_")
        }
        w1 = slo_src.get("window_1m") or {}
        slo_out["window_1m_requests"] = int(
            (w1.get("met") or 0) + (w1.get("missed") or 0)
        )
        scaler = self.autoscaler
        # Object-store tier (version 5, ISSUE 14): shared-store occupancy,
        # the cross-host dedupe ratio, and wake counts — the autoscaler's
        # "drain-then-shrink is cheap here" signal.  Version 6 (ISSUE 17)
        # adds store health: breaker state (the dp-aggregate max, so any
        # replica's open breaker surfaces), retry/timeout counters, and
        # store_available — False tells a controller the pre-scale-in
        # drain will be skipped (capacity beats warm state).  Null when
        # KAFKA_TPU_KV_OBJECT_DIR is unset.
        obj = snap.get("object_tier") or None
        object_section = None
        if obj:
            tried = (obj.get("object_puts", 0)
                     + obj.get("dedupe_hits", 0))
            breaker_gauge = int(obj.get("store_breaker_state", 0))
            object_section = {
                "store_bytes": obj.get("store_bytes", 0),
                "store_objects": obj.get("store_objects", 0),
                "dedupe_ratio": round(
                    obj.get("dedupe_hits", 0) / tried, 4
                ) if tried else 0.0,
                "wake_threads": obj.get("wake_threads", 0),
                "wake_tokens": obj.get("wake_tokens", 0),
                "breaker_state": {0: "closed", 1: "half_open",
                                  2: "open"}.get(breaker_gauge, "open"),
                "breaker_opens": obj.get("store_breaker_opens", 0),
                "store_available": breaker_gauge != 2,
                "store_retries": obj.get("store_retries", 0),
                "store_timeouts": obj.get("store_timeouts", 0),
                "store_errors": (obj.get("object_put_failures", 0)
                                 + obj.get("object_get_failures", 0)),
                "probe_neg_cached": obj.get("store_probe_neg_cached", 0),
                # version 8 (ISSUE 19): wake-prefetch effectiveness —
                # hits vs wasted tells a controller whether the staging
                # budget is sized right (all zeros = prefetch off)
                "prefetch": {
                    "hits": obj.get("prefetch_hits", 0),
                    "wasted": obj.get("prefetch_wasted", 0),
                    "bytes": obj.get("prefetch_bytes", 0),
                    "inflight": obj.get("prefetch_inflight", 0),
                },
            }
        # Device-truth sections (version 7, ISSUE 18).  compiles: the
        # process-wide observatory ring summary — storm_active is the
        # "XLA is recompiling under live traffic" veto input (null when
        # KAFKA_TPU_COMPILE_RING=0).  memory: measured HBM per replica
        # plus the worst-case aggregate — a controller sizes scale-up
        # against MEASURED headroom (min across replicas) and treats
        # plan_skew > 1 as "the plan under-charges, trust the device".
        from ..runtime import compile_log

        obs = compile_log.get()
        compiles_section = (
            obs.signals_section() if obs is not None else None
        )
        mem_reps: List[Dict[str, Any]] = []
        for i, e in enumerate(replicas):
            mm = getattr(e, "memory_monitor", None)
            sec = mm.section() if mm is not None else None
            if not sec or sec.get("source") == "none":
                continue
            mem_reps.append({
                "replica": i,
                "source": sec["source"],
                "hbm_bytes_in_use": sec["hbm_bytes_in_use"],
                "hbm_bytes_limit": sec["hbm_bytes_limit"],
                "hbm_headroom_bytes": sec["hbm_headroom_bytes"],
                "hbm_plan_skew": sec["hbm_plan_skew"],
                "hbm_pressure": sec["hbm_pressure"],
            })
        memory_section = None
        if mem_reps:
            memory_section = {
                "headroom_bytes": min(
                    r["hbm_headroom_bytes"] for r in mem_reps
                ),
                "plan_skew": max(r["hbm_plan_skew"] for r in mem_reps),
                "pressure": max(r["hbm_pressure"] for r in mem_reps),
                "replicas": mem_reps,
            }
        # Agent-native scheduling (version 9, ISSUE 20).  awaiting_*
        # describes threads parked mid-tool-gap: NOT load (no decode
        # slot, KV in lower tiers) — a controller must exclude them
        # from demand sizing and read them only as a wake-traffic
        # forecast.  All zeros knobs-off.
        ag = snap.get("agent") or {}
        agent_section = {
            "awaiting_threads": ag.get("agent_awaiting_threads", 0),
            "awaiting_bytes": ag.get("agent_awaiting_bytes", 0),
            "gaps": ag.get("agent_gaps", 0),
            "gap_demotions": ag.get("agent_gap_demotions", 0),
            "gap_pages_demoted": ag.get("agent_gap_pages_demoted", 0),
            "gap_bytes_demoted": ag.get("agent_gap_bytes_demoted", 0),
            "gap_cancelled": ag.get("agent_gap_cancelled", 0),
            "hint_hits": ag.get("agent_hint_hits", 0),
            "hint_misses": ag.get("agent_hint_misses", 0),
            "bg_queue_depth": ag.get("bg_queue_depth", 0),
            "bg_admitted": ag.get("bg_admitted", 0),
            "bg_chunks": ag.get("bg_chunks", 0),
            "bg_yields": ag.get("bg_yields", 0),
        }
        return {
            # version 9 (ISSUE 20): agent-native scheduling — the
            # ``agent`` section (awaiting-tool threads + demoted bytes,
            # expected-return hint hit/miss, gap-demotion counters,
            # background-class queue/admit/chunk/yield).  Contract:
            # awaiting-tool threads are NOT load — exclude them when
            # sizing; they only forecast wake traffic.
            # version 8 (ISSUE 19): zero-host-copy movement — the
            # object_tier section gains ``prefetch`` (wake-prefetch
            # hits/wasted/bytes/inflight: zeros when
            # KAFKA_TPU_WAKE_PREFETCH_MB is unset) and the disagg
            # section carries the ship-transport split
            # (disagg_ship_host_runs / disagg_ship_device_runs — host +
            # device sum to disagg_shipped_runs) plus the host-staging
            # peak gauge (disagg_ship_staging_bytes, 0 under the
            # device transport).
            # version 7 (ISSUE 18): device-truth sections — compiles
            # (observatory ring summary + storm_active, null when
            # KAFKA_TPU_COMPILE_RING=0) and memory (measured HBM
            # headroom/plan_skew/pressure, per replica + worst-case
            # aggregate, null before the first poll or without a
            # monitor).  version 6 (ISSUE 17): object_tier section gains store
            # health — breaker_state/breaker_opens/store_available plus
            # retry/timeout/error and negative-probe counters (the
            # StoreGuard resilience layer).  Version 5 (ISSUE 14) added
            # the object_tier section (shared-store bytes/objects,
            # dedupe ratio, wake counts — null without
            # KAFKA_TPU_KV_OBJECT_DIR).  Version 4 (ISSUE 13) added the
            # autoscaler section (control-loop mode, degradation-ladder
            # rung, cooldowns, last decision — null when
            # KAFKA_TPU_AUTOSCALE is off) and slo.window_1m_requests
            # (verdict count behind the 1m attainment gauge).  Version 3
            # (ISSUE 12) added the pools section and disagg ship
            # counters; version 2 (ISSUE 11) the anomalies section,
            # per-replica anomalies_active, and the
            # measured-utilization fields under utilization.*.
            "version": 9,
            "dp": len(replicas),
            "queue": dict(snap.get("queue") or {}),
            "anomalies": anomalies,
            "agent": agent_section,
            "compiles": compiles_section,
            "memory": memory_section,
            "pools": pools,
            "object_tier": object_section,
            "disagg": {
                k: v for k, v in disagg.items()
                if k not in ("pools", "ship_ms")
            } or None,
            "autoscaler": (
                scaler.signals_section() if scaler is not None else None
            ),
            "batch": {
                "occupancy": occupancy,
                "occupancy_frac": round(occupancy / max_batch, 4)
                if max_batch else 0.0,
                "active": engine.num_active,
                "max_batch": max_batch,
                "slots_total": max_batch * len(replicas),
            },
            "slo": slo_out,
            "utilization": snap.get("utilization") or {},
            "replicas": per_replica,
            "supervisor": {
                k: v for k, v in snap["replica_supervisor"].items()
                if k != "health"
            } if snap.get("replica_supervisor") else None,
        }

    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful drain: let in-flight requests finish, then cancel.

        Returns True when everything completed within the timeout.  The
        caller (server shutdown) has already stopped admitting, so
        has_work is monotone-decreasing except for requests racing through
        the worker inbox — those get their terminal events either by
        finishing or by the cancel sweep below.
        """
        deadline = time.monotonic() + timeout_s
        replicas = self._replicas()
        while time.monotonic() < deadline:
            if not any(e.has_work for e in replicas):
                return True
            await asyncio.sleep(0.05)

        leftover = [rid for e in replicas
                    for rid, _ in _torn_items(e._requests)]
        if leftover:
            logger.warning(
                "drain timeout after %.1fs: cancelling %d in-flight "
                "request(s)", timeout_s, len(leftover),
            )
            for rid in leftover:
                self.worker.cancel(rid)
            # give the engine thread a moment to process the cancels so
            # every stream sees its terminal event before teardown
            settle = time.monotonic() + min(2.0, timeout_s)
            while time.monotonic() < settle and any(
                e.has_work for e in replicas
            ):
                await asyncio.sleep(0.02)
        return not leftover

    async def resize_dp(self, dp: int, drain_timeout_s: float = 30.0,
                        roles: Any = _ROLES_KEEP) -> bool:
        """Rebuild the DP replica set at a new dp count (replica loss /
        scale-down) while WAITING requests survive the rebuild.

        `roles` (ISSUE 13 satellite) optionally re-shapes the role pools
        in the same rebuild: a "prefill:P,decode:D" spec validated by the
        same parse_dp_roles rules (P + D must equal `dp`), None/""
        dissolves the pools back to colocated serving, and the default
        keeps the current spec (re-derived for the new dp, today's
        behavior) — the autoscaler and /admin/resize operators share
        this one path.

        The drain/restart topology story (ISSUE 2): started lanes own
        device state that cannot move across engines, so they get
        `drain_timeout_s` to retire naturally; leftovers are cancelled
        (each still receives its terminal event).  Queued requests are
        never touched — they ride through the rebuild and serve from the
        new replicas.  Returns True when no request had to be cancelled.

        Engine restructuring happens with the worker thread PARKED
        (EngineWorker.pause): the single-writer invariant means a parked
        worker cannot race the rebuild, and queued submits/cancels simply
        wait in the inbox for resume().  One resize runs at a time
        (asyncio lock), and the admission gate 429s new serving traffic
        for the duration — the drain then works a finite set and must
        converge.
        """
        rebuild = getattr(self.engine, "rebuild", None)
        if rebuild is None:
            raise ValueError(
                "resize_dp requires a DataParallelEngines engine "
                "(single-engine deployments have no replica topology)"
            )
        # validate the device budget BEFORE draining: an impossible dp
        # must fail up front, not after in-flight requests were cancelled
        validate = getattr(self.engine, "validate_dp", None)
        if validate is not None:
            validate(dp)
        if roles is not _ROLES_KEEP:
            # validate the role spec BEFORE draining too: a bad spec
            # must fail up front, not after in-flight work was cancelled
            from ..runtime.dp_router import validate_roles_spec

            validate_roles_spec(roles, dp)
        async with self._resize_lock:
            if self._orphan_rebuild is not None:
                # a previous resize was cancelled mid-rebuild: its thread
                # may STILL be mutating engines.  Starting a second
                # rebuild now would run two concurrent mutators (and the
                # orphan's completion would resume the worker mid-rebuild)
                # — wait the orphan out first.  Its done-callback was
                # added before this await's, so by the time we continue
                # the worker resume/flag-clear has already run.
                try:
                    await asyncio.shield(self._orphan_rebuild)
                except Exception:
                    # already logged by the orphan's done-callback; the
                    # NEW resize proceeds and rebuilds from current state
                    pass
                self._orphan_rebuild = None
            try:
                return await self._resize_locked(
                    rebuild, dp, drain_timeout_s, roles
                )
            finally:
                # a cancelled resize (client timeout mid-drain) must never
                # leave the worker parked — resume() is idempotent, and a
                # permanently paused worker is a total serving outage.
                # EXCEPT while a cancelled rebuild thread is still
                # mutating engines: then the rebuild's done-callback owns
                # the resume (resuming earlier would race the rebuild).
                if not self._rebuild_owns_resume:
                    self.worker.resume()

    async def _resize_locked(self, rebuild, dp: int,
                             drain_timeout_s: float,
                             roles: Any = _ROLES_KEEP) -> bool:
        def _started(e) -> bool:
            # pending disaggregated hand-offs are started work too: their
            # pages + un-emitted first token complete at step cadence, so
            # the drain loop below resumes the worker until they clear
            return bool(e.num_active or e.parked or e._pending
                        or getattr(e, "handoffs", None))

        clean = True
        deadline = time.monotonic() + drain_timeout_s
        while True:
            # park first, then look: an unparked worker could seat a
            # waiting request between our check and the rebuild
            if not await asyncio.to_thread(self.worker.pause):
                self.worker.resume()  # half-engaged pause must not linger
                raise RuntimeError("engine worker did not pause")
            busy = [e for e in self._replicas() if _started(e)]
            if not busy:
                break
            self.worker.resume()
            if time.monotonic() >= deadline:
                if time.monotonic() >= deadline + drain_timeout_s + 5.0:
                    # cancels were dispatched and still didn't land
                    raise RuntimeError(
                        "resize_dp: started work did not drain"
                    )
                # sweep EVERY iteration past the deadline: requests the
                # worker seated after an earlier sweep (inbox stragglers)
                # get cancelled too, so the finite set keeps shrinking.
                # Worker is resumed, hence the torn-tolerant snapshot.
                clean = False
                ids = [rid for e in busy
                       for rid, req in _torn_items(e._requests)
                       if req.state != "waiting"]
                if ids:
                    logger.warning(
                        "resize_dp: drain timeout; cancelling %d started "
                        "request(s)", len(ids),
                    )
                    for rid in ids:
                        self.worker.cancel(rid)
            await asyncio.sleep(0.02)
        # Engine reconstruction compiles/places device arrays for seconds;
        # with the worker parked the rebuild is single-writer safe from
        # ANY thread, so run it off the event loop — /health (and every
        # other handler) stays responsive during the rebuild instead of
        # blocking behind it.
        from ..runtime import compile_log

        # rebuild compiles are expected, not a storm: phase the compile
        # observatory here (not in the HTTP handler) so act-mode
        # autoscaler resizes get the same treatment (ISSUE 18)
        compile_log.set_phase("rebuild")
        fut = asyncio.get_running_loop().run_in_executor(
            None, lambda: (
                rebuild(dp=dp) if roles is _ROLES_KEEP
                else rebuild(dp=dp, roles=roles)
            )
        )
        try:
            await asyncio.shield(fut)
        except asyncio.CancelledError:
            if not fut.done():
                # the rebuild thread is STILL mutating engines: resuming
                # the worker now (the callers' finally blocks) would race
                # it — hand the resume to the rebuild's completion, and
                # leave the future behind so the NEXT resize waits it out
                # before touching the topology
                self._rebuild_owns_resume = True
                self._orphan_rebuild = fut

                def _resume(f) -> None:
                    self._rebuild_owns_resume = False
                    self.worker.resume()
                    compile_log.set_phase("first_traffic")
                    # the cancelled caller never sees the rebuild's fate:
                    # a silent rebuild failure (old/half topology still
                    # serving) must at least reach the logs
                    exc = None if f.cancelled() else f.exception()
                    if exc is not None:
                        logger.error(
                            "orphaned topology rebuild (resize was "
                            "cancelled mid-flight) FAILED: %s — the "
                            "previous topology may still be serving; "
                            "retry /admin/resize", exc,
                        )

                fut.add_done_callback(_resume)
            raise
        finally:
            if not self._rebuild_owns_resume:
                self.worker.resume()
                compile_log.set_phase("first_traffic")
        return clean

    async def drain_replica(self, replica: int) -> Dict[str, Any]:
        """Flush one replica's warm KV state into the shared object
        store (POST /admin/drain/{replica}, ISSUE 14): every cached
        radix run archived content-addressed + every thread's sleep
        manifest written, so a subsequent scale-in removing the replica
        discards no warm conversation — dormant threads wake on the
        survivors (cache_source="object_tier") instead of
        re-prefilling.  Non-destructive and idempotent (re-archiving
        present content is a reference-only dedupe).

        Runs with the worker PARKED (the flush gathers pool pages and
        walks the radix tree — both single-writer engine state) and
        serialized against resizes via the same lock, so a drain can
        never race the rebuild that follows it."""
        return (await self.drain_replicas([replica]))[0]

    async def drain_replicas(self, indices) -> List[Dict[str, Any]]:
        """drain_replica over several replicas under ONE worker pause —
        the autoscaler's pre-scale-in drain covers the whole fleet (the
        rebuild recreates every engine), and one pause/flush cycle per
        replica would stall serving N times for N flushes."""
        indices = list(indices)
        async with self._resize_lock:
            # resolve the replicas UNDER the lock: a resize rebuilds the
            # replica list wholesale, and a pre-lock snapshot could pass
            # a stale bounds check and then flush a torn-down engine
            replicas = self._replicas()
            sleeps = []
            for i in indices:
                if not 0 <= i < len(replicas):
                    raise ValueError(
                        f"replica {i} out of range (dp={len(replicas)})"
                    )
                sleep = getattr(replicas[i], "sleep_to_object", None)
                if sleep is None:
                    raise ValueError(
                        "this engine cannot drain to an object store"
                    )
                sleeps.append(sleep)
            if not await asyncio.to_thread(self.worker.pause):
                self.worker.resume()
                raise RuntimeError("engine worker did not pause")
            try:
                # the tree walks + D2H gathers can take seconds on warm
                # replicas: run off the event loop so /health stays live
                # (sequential inside one executor job — the flushes
                # mutate device state under the single-writer contract)
                all_stats = await asyncio.get_running_loop(
                ).run_in_executor(None, lambda: [s() for s in sleeps])
            finally:
                self.worker.resume()
        for i, stats in zip(indices, all_stats):
            stats["replica"] = i
        return all_stats

    def get_model_info(self, model: Optional[str] = None) -> Dict[str, Any]:
        return {
            "id": model or self.model_name,
            "provider": self.provider_name,
            "max_context": self.model_cfg.max_context,
            "max_window": self.engine.ecfg.max_window,
            "vocab_size": self.model_cfg.vocab_size,
            "supports_tools": True,
            "supports_streaming": True,
            # draft-free speculative decoding depth (0 = off): surfaced so
            # operators can confirm the serving shape without reading env
            "speculative_k": self.engine.ecfg.speculative_k,
            # on-device grammar FSM for constrained tool-call decoding
            # (KAFKA_TPU_GRAMMAR_ONDEVICE; llm/constrained.py)
            "grammar_ondevice": _grammar_ondevice_enabled(),
        }

    def build_tool_call_mask_fn(
        self,
        tools: Optional[List[Dict[str, Any]]],
        tool_choice: Any = "required",
    ):
        """Constrained decoding over the local sampler (llm/constrained.py):
        the returned fn plugs into GenRequest.logits_mask_fn and forces
        schema-valid tool-call JSON."""
        from .constrained import build_tool_call_mask_fn

        return build_tool_call_mask_fn(self.tokenizer, tools or [], tool_choice)

    def get_available_models(self) -> List[Dict[str, Any]]:
        return [
            {
                "id": self.model_name,
                "object": "model",
                "owned_by": "kafka-tpu",
                "created": 0,
            }
        ]

    # ------------------------------------------------------------------

    async def stream_completion(
        self,
        messages: Sequence[MessageLike],
        model: Optional[str] = None,
        temperature: float = 0.7,
        max_tokens: Optional[int] = None,
        tools: Optional[List[Dict[str, Any]]] = None,
        top_p: float = 1.0,
        top_k: int = 0,
        seed: Optional[int] = None,
        logits_mask_fn=None,
        prefix_key: Optional[str] = None,
        background: bool = False,
        **kwargs: Any,
    ) -> AsyncIterator[StreamChunk]:
        self.validate_messages(messages)
        dicts = to_message_dicts(messages)
        # Image parts: served through the vision tower when the model has
        # one (Llava-style soft prompt, models/vision.py — newest-19
        # pruning first, reference src/llm/portkey.py:276); a text-only
        # model rejects loudly with a typed 400 rather than silently
        # flattening (the model must not answer as if it saw an image).
        n_images = count_images(dicts)
        override_pos = override_rows = None
        if n_images:
            if self._encode_images is None:
                raise UnsupportedContentError(
                    n_images, provider=self.provider_name
                )
            import numpy as _np

            from .images import expand_placeholders, extract_images
            from .utils import prune_images

            vcfg = self.model_cfg.vision
            dicts = prune_images(dicts)

            def _prep():
                # PIL decode + ViT forward (first call also jit-compiles)
                # are CPU/TPU-blocking: off the event loop, or every
                # in-flight stream stalls for the duration
                d2, pixels = extract_images(dicts, vcfg.image_size)
                emb = self._encode_images(_np.stack(pixels))
                return d2, len(pixels), _np.asarray(emb, _np.float32)

            dicts, n_pix, embeds = await asyncio.to_thread(_prep)
            ids = self.tokenizer.encode_chat(dicts, tools=tools)
            sentinel_id = self.tokenizer.encode("\x00")[0]
            prompt_ids, override_pos = expand_placeholders(
                ids, sentinel_id, self.model_cfg.image_token_id,
                vcfg.num_patches, n_pix,
            )
            override_rows = embeds.reshape(-1, self.model_cfg.hidden_size)
            # identical placeholder ids for DIFFERENT image bytes must
            # never share prefix-cached KV (the cache keys on token ids)
            prefix_key = None
        else:
            prompt_ids = self.tokenizer.encode_chat(dicts, tools=tools)
        if len(prompt_ids) > self.max_prompt_tokens:
            raise ContextLengthError(
                len(prompt_ids), self.max_prompt_tokens, self.provider_name
            )

        # On-device grammar FSM (ISSUE 7, KAFKA_TPU_GRAMMAR_ONDEVICE):
        # lower the tool-call mask into a device-resident token DFA so the
        # constrained lane advances inside the jitted decode step with
        # zero host round trips.  Cached per (tokenizer, schema, vocab);
        # small-vocab compiles run synchronously off the event loop, while
        # LARGE-vocab schemas (> KAFKA_TPU_GRAMMAR_SYNC_VOCAB) compile on
        # a background worker — the first call returns None immediately
        # (host-mask path, no multi-second stall) and later calls flip to
        # on-device once the table lands (constrained_compile_pending
        # gauge).  None (disabled, a custom mask fn, or an uncompilable
        # grammar) keeps the host micro-batch path.
        grammar = None
        if logits_mask_fn is not None:
            from .constrained import compile_grammar_for_mask_fn

            grammar = await asyncio.to_thread(
                compile_grammar_for_mask_fn, logits_mask_fn,
                self.model_cfg.vocab_size,
            )

        completion_id = new_completion_id()
        model_id = model or self.model_name
        req = GenRequest(
            request_id=f"{completion_id}-{next(self._counter)}",
            prompt_ids=prompt_ids,
            max_new_tokens=max_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            seed=seed if seed is not None else 0,
            stop_token_ids=tuple(self.tokenizer.stop_ids),
            logits_mask_fn=logits_mask_fn,
            grammar=grammar,
            prefix_key=prefix_key,
            # background class (ISSUE 20): tool-result prefill and
            # in-engine compaction ride idle capacity, yielding to
            # interactive work at every scheduler iteration
            background=background,
            override_pos=override_pos,
            override_rows=override_rows,
            # carry the ambient trace context across the thread boundary:
            # the engine thread records queue/prefill/decode/emit spans
            # against it (None = untraced, one branch per span site)
            trace=current_trace(),
        )
        loop = asyncio.get_running_loop()
        events = self.worker.submit(req, loop)

        # role header first (OpenAI convention)
        yield StreamChunk(role="assistant", id=completion_id, model=model_id)

        detok = IncrementalDetokenizer(self.tokenizer)
        # tool-call detection: undecided until the first non-space char;
        # "{" / "[" switches to buffering mode, anything else streams.
        mode = "undecided"
        buffered: List[str] = []
        n_tokens = 0
        try:
            while True:
                ev: TokenEvent = await events.get()
                if ev.finish_reason and ev.finish_reason.startswith(
                    "rejected:"
                ):
                    # engine-thread admission backstop (queue filled
                    # between the server gate's check and our submit)
                    parts = ev.finish_reason.split(":", 2)
                    try:
                        retry = float(parts[1])
                    except (IndexError, ValueError):
                        retry = 5.0
                    raise ServerOverloadedError(
                        retry, provider=self.provider_name
                    )
                if ev.finish_reason and ev.finish_reason.startswith("error:"):
                    raise LLMProviderError(
                        ev.finish_reason[len("error:") :],
                        provider=self.provider_name,
                    )
                if ev.finish_reason == "cancelled":
                    raise asyncio.CancelledError("generation cancelled")
                text = ""
                if ev.token_id is not None:
                    n_tokens += 1
                    text = detok.push(ev.token_id)
                if ev.finished:
                    text += detok.flush()
                if text:
                    if mode == "undecided":
                        probe = ("".join(buffered) + text).lstrip()
                        if not probe:
                            buffered.append(text)
                        elif probe[0] in "[{":
                            mode = "tool"
                            buffered.append(text)
                        else:
                            mode = "text"
                            pending = "".join(buffered) + text
                            buffered = []
                            yield StreamChunk(
                                content=pending, id=completion_id, model=model_id
                            )
                    elif mode == "tool":
                        buffered.append(text)
                    else:
                        yield StreamChunk(
                            content=text, id=completion_id, model=model_id
                        )
                if ev.finished:
                    final = self._finalize(
                        mode, buffered, ev, completion_id, model_id,
                        len(prompt_ids), n_tokens,
                        # FIRST-admission radix share (frozen at prefill
                        # start): a preemption or disaggregated-hand-off
                        # resume re-attaches the whole prefix, which must
                        # not read as client-saved compute
                        cached_tokens=req.usage_cached_tokens or 0,
                    )
                    if any(c.finish_reason == "tool_calls" for c in final):
                        # the thread is about to leave for a tool call:
                        # start the demote linger + expected-return hint
                        # (engine-thread op via the inbox; no-op with
                        # KAFKA_TPU_AGENT_DEMOTE unset)
                        self.worker.note_tool_gap(req.prefix_key)
                    for chunk in final:
                        yield chunk
                    return
        finally:
            if req.state != "finished":
                self.worker.cancel(req.request_id)

    def _finalize(
        self,
        mode: str,
        buffered: List[str],
        ev: TokenEvent,
        completion_id: str,
        model_id: str,
        prompt_tokens: int,
        completion_tokens: int,
        cached_tokens: int = 0,
    ) -> List[StreamChunk]:
        """Terminal chunks: flush buffers, resolve tool calls, report usage."""
        chunks: List[StreamChunk] = []
        finish = ev.finish_reason or "stop"
        text = "".join(buffered)
        tool_calls = parse_tool_call_text(text) if mode == "tool" else None
        if tool_calls:
            deltas = [
                {
                    "index": i,
                    "id": tc["id"],
                    "type": "function",
                    "function": tc["function"],
                }
                for i, tc in enumerate(tool_calls)
            ]
            chunks.append(
                StreamChunk(tool_calls=deltas, id=completion_id, model=model_id)
            )
            finish = "tool_calls"
        elif text:
            # buffered text that didn't parse as a tool call: emit verbatim
            chunks.append(
                StreamChunk(content=text, id=completion_id, model=model_id)
            )
        usage = Usage(
            prompt_tokens=prompt_tokens,
            completion_tokens=completion_tokens,
            total_tokens=prompt_tokens + completion_tokens,
            # OpenAI-compatible prompt_tokens_details.cached_tokens: the
            # prompt span served from radix-cached KV pages (own- or
            # cross-thread) instead of prefill compute
            cached_prompt_tokens=cached_tokens,
        )
        chunks.append(
            StreamChunk(
                finish_reason=finish,
                id=completion_id,
                model=model_id,
                usage=usage.to_dict(),
            )
        )
        return chunks

    async def aclose(self) -> None:
        self.worker.stop()
