"""Context-compaction primitives: error classification, safe splitting,
structural validation, and the provider ABC.

Behavior parity with the reference (src/llm/context_compaction/base.py):

* `is_context_length_error` (:10-65) — multi-provider string-pattern
  classifier, extended here with a fast path for the engine's typed
  `ContextLengthError` (the local engine raises pre-flight; the patterns
  remain so foreign error strings still classify).
* `find_safe_split_point` (:68-112) — never separates an
  assistant-with-tool_calls message from the tool results answering it.
* `validate_message_structure` (:115-168) — drops orphan tool results and
  empty assistant messages.
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Sequence

from ...core.types import ContextLengthError

# Error-string fragments that indicate a context-window overflow across
# provider families (the reference matched these against remote API errors).
CONTEXT_LENGTH_PATTERNS = (
    "context_length_exceeded",
    "context length",
    "maximum context",
    "prompt is too long",
    # anthropic: "input length and `max_tokens` exceed context limit" —
    # matched on the distinctive phrase, not the bare "max_tokens" token,
    # so validation errors like "max_tokens must be positive" don't
    # trigger a pointless compaction retry
    "exceed context limit",
    "too many tokens",
    "token limit",
    "input is too long",
    "request too large",
    "exceeds the limit",
    "reduce the length",
    "string too long",
)


def is_context_length_error(error: BaseException) -> bool:
    """True when `error` indicates the prompt exceeded the model context."""
    if isinstance(error, ContextLengthError):
        return True
    text = str(error).lower()
    return any(p in text for p in CONTEXT_LENGTH_PATTERNS)


def _opens_tool_calls(msg: Dict[str, Any]) -> bool:
    return msg.get("role") == "assistant" and bool(msg.get("tool_calls"))


def find_safe_split_point(messages: Sequence[Dict[str, Any]], target: int) -> int:
    """Largest split index <= target that doesn't sever a tool-call pair.

    Messages before the split are summarized/dropped; messages from the
    split on are kept.  A split is unsafe if it would keep a `tool` result
    whose assistant-with-tool_calls message was summarized away (orphan), or
    summarize results while keeping their assistant message is impossible by
    construction (results follow their call).  Walk the target backward to
    the nearest safe boundary; index 0 is always safe.
    """
    target = max(0, min(target, len(messages)))
    s = target
    while s > 0:
        # unsafe iff the message AT the boundary is a tool result answering
        # a call opened before the boundary, or the boundary lands between
        # an assistant-with-tool_calls and its first result
        at = messages[s] if s < len(messages) else None
        before = messages[s - 1]
        if at is not None and at.get("role") == "tool":
            s -= 1
            continue
        if _opens_tool_calls(before):
            s -= 1
            continue
        return s
    return 0


def validate_message_structure(
    messages: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Drop orphan tool results and empty assistant messages.

    Same window semantics as core.sanitize but operating on dicts (the
    compaction layer works on OpenAI-wire dicts throughout).
    """
    out: List[Dict[str, Any]] = []
    open_ids: set = set()
    for m in messages:
        role = m.get("role")
        if role == "assistant":
            if not m.get("content") and not m.get("tool_calls"):
                continue  # empty assistant message
            if m.get("tool_calls"):
                open_ids = {
                    tc.get("id") for tc in m["tool_calls"] if tc.get("id")
                }
            else:
                open_ids = set()
            out.append(m)
        elif role == "tool":
            tcid = m.get("tool_call_id")
            if tcid and tcid in open_ids:
                open_ids.discard(tcid)
                out.append(m)
            # else: orphan, dropped
        else:
            open_ids = set()
            out.append(m)
    return out


class ContextCompactionProvider(abc.ABC):
    """Shrinks a conversation that no longer fits the model context.

    Parity: reference src/llm/context_compaction/base.py (ABC) — `compact`
    returns a new message list expected to fit; implementations must never
    produce orphan tool messages.

    `fit`, when given, is the caller's token-aware budget predicate
    (True = the message list fits).  The caller knows request overhead the
    provider cannot — tool definitions added at render time — so a passed
    fit overrides any provider-internal default.
    """

    @abc.abstractmethod
    async def compact(
        self,
        messages: List[Dict[str, Any]],
        model: str | None = None,
        fit: Any | None = None,
    ) -> List[Dict[str, Any]]:
        raise NotImplementedError
