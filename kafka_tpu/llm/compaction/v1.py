"""Compaction strategies: LLM summarization with truncation fallback.

Parity with the reference's two providers
(src/llm/context_compaction/v1.py:49-313):

* `SummarizationCompactionProvider` — summarize the oldest `summarize_ratio`
  of the conversation via an LLM call, keep the rest verbatim, insert the
  summary as a system message (with `cache_control: ephemeral` metadata, as
  the reference does for Anthropic prompt caching); falls back to safe
  truncation on any failure.
* `TruncationCompactionProvider` — keep system messages + the last N
  conversation messages at a tool-pair-safe boundary.

Unlike the reference, the summarization call goes to the *local* TPU
provider — no second network hop — and the target size can be validated
pre-flight by token counting when the provider exposes it.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional

from ..base import LLMProvider
from .base import (
    ContextCompactionProvider,
    find_safe_split_point,
    validate_message_structure,
)

# Optional fit predicate: True when a message list fits the model context.
# The local engine can count tokens exactly (TPULLMProvider.count_prompt_
# tokens), which the reference never could — its compaction was blind
# message-count heuristics plus one retry. With a `fit`, both strategies
# tighten until the result actually fits.
FitFn = Callable[[List[Dict[str, Any]]], bool]


def fit_from_provider(llm: LLMProvider, margin: int = 256) -> Optional[FitFn]:
    """Build a token-aware fit predicate from a provider that can count.

    `margin` reserves room for the generation itself.
    """
    count = getattr(llm, "count_prompt_tokens", None)
    limit = getattr(llm, "max_prompt_tokens", None)
    if count is None or limit is None:
        return None
    # never let the generation margin eat more than half a small window
    budget = limit - min(margin, limit // 2)
    return lambda msgs: count(msgs) <= max(1, budget)

logger = logging.getLogger("kafka_tpu.compaction")

SUMMARY_SYSTEM_PROMPT = (
    "You are a conversation summarizer. Produce a concise but complete "
    "summary of the conversation so far: user goals, decisions made, tool "
    "calls and their key results, current state, and any unresolved items. "
    "Write it so an assistant can seamlessly continue the conversation."
)

SUMMARY_PREFIX = "[Conversation summary — earlier messages were compacted]\n"

# Per-model max summary output budget (reference: v1.py:20-46 kept a
# per-model table; the local engine reads its own config instead, this
# table only caps the request).
DEFAULT_MAX_SUMMARY_TOKENS = 1024


def _content_len(m: Dict[str, Any]) -> int:
    c = m.get("content")
    if isinstance(c, str):
        return len(c)
    if isinstance(c, list):
        return sum(len(p.get("text", "")) for p in c if isinstance(p, dict))
    return 0


def _halve_content(m: Dict[str, Any]) -> Dict[str, Any]:
    """Copy of `m` with its longest text halved, newest chars kept."""
    marker = "[…trimmed…] "
    c = m.get("content")
    m = dict(m)
    if isinstance(c, str):
        m["content"] = marker + c[len(c) // 2 :]
    elif isinstance(c, list):
        parts = [dict(p) if isinstance(p, dict) else p for p in c]
        longest = max(
            (p for p in parts if isinstance(p, dict) and p.get("text")),
            key=lambda p: len(p["text"]),
            default=None,
        )
        if longest is not None:
            longest["text"] = marker + longest["text"][len(longest["text"]) // 2 :]
        m["content"] = parts
    return m


def _trim_contents(messages: List[Dict[str, Any]], fit: FitFn,
                   max_rounds: int = 64) -> List[Dict[str, Any]]:
    """Halve the largest message contents until `fit` passes (or floor)."""
    out = list(messages)
    for _ in range(max_rounds):
        if fit(out):
            return out
        i = max(range(len(out)), key=lambda j: _content_len(out[j]), default=None)
        if i is None or _content_len(out[i]) <= 32:
            break  # nothing meaningful left to trim
        out[i] = _halve_content(out[i])
    return out


def _split_system(messages: List[Dict[str, Any]]):
    """Leading system messages vs the conversation body."""
    i = 0
    while i < len(messages) and messages[i].get("role") == "system":
        i += 1
    return list(messages[:i]), list(messages[i:])


class TruncationCompactionProvider(ContextCompactionProvider):
    """Keep system messages + the newest `keep_last` conversation messages.

    Parity: reference v1.py:242-313 (keep-last-50 default).
    """

    def __init__(self, keep_last: int = 50, fit: Optional[FitFn] = None):
        self.keep_last = keep_last
        self.fit = fit

    async def compact(
        self,
        messages: List[Dict[str, Any]],
        model: Optional[str] = None,
        fit: Optional[FitFn] = None,
    ) -> List[Dict[str, Any]]:
        eff_fit = fit or self.fit
        system_msgs, convo = _split_system(messages)
        keep = self.keep_last
        out = validate_message_structure(messages)
        while len(convo) > 0:
            if len(convo) > keep:
                split = find_safe_split_point(convo, len(convo) - keep)
                out = validate_message_structure(system_msgs + convo[split:])
            if eff_fit is None or eff_fit(out) or keep <= 1:
                break
            keep //= 2  # still over budget: tighten and retry
        if eff_fit is not None and not eff_fit(out):
            # last resort: individual messages larger than the window —
            # trim their text content (newest chars kept) until it fits
            out = _trim_contents(out, eff_fit)
        if len(messages) != len(out):
            logger.info(
                "truncation compaction: %d -> %d messages",
                len(messages), len(out),
            )
        return out


class SummarizationCompactionProvider(ContextCompactionProvider):
    """Summarize the oldest portion of the conversation via an LLM call.

    Parity: reference v1.py:49-239. `summarize_ratio` of the conversation
    (by message count) is summarized; the remainder is kept verbatim after
    a tool-pair-safe split.
    """

    def __init__(
        self,
        llm_provider: LLMProvider,
        model: Optional[str] = None,
        summarize_ratio: float = 0.75,
        min_messages: int = 10,
        max_summary_tokens: int = DEFAULT_MAX_SUMMARY_TOKENS,
        temperature: float = 0.3,
        fallback: Optional[ContextCompactionProvider] = None,
        fit: Optional[FitFn] = None,
    ):
        self.llm = llm_provider
        self.model = model
        self.summarize_ratio = summarize_ratio
        self.min_messages = min_messages
        self.max_summary_tokens = max_summary_tokens
        self.temperature = temperature
        self.fit = fit if fit is not None else fit_from_provider(llm_provider)
        self.fallback = fallback or TruncationCompactionProvider(fit=self.fit)

    async def compact(
        self,
        messages: List[Dict[str, Any]],
        model: Optional[str] = None,
        fit: Optional[FitFn] = None,
    ) -> List[Dict[str, Any]]:
        eff_fit = fit or self.fit
        system_msgs, convo = _split_system(messages)
        if len(convo) < self.min_messages:
            # too short to summarize meaningfully — safe truncation
            return await self.fallback.compact(messages, model, fit=eff_fit)
        target = int(len(convo) * self.summarize_ratio)
        split = find_safe_split_point(convo, target)
        if split <= 0:
            return await self.fallback.compact(messages, model, fit=eff_fit)
        to_summarize, kept = convo[:split], convo[split:]
        try:
            summary = await self._summarize(to_summarize, model or self.model)
        except Exception as e:
            logger.warning("summarization failed (%s); falling back", e)
            return await self.fallback.compact(messages, model, fit=eff_fit)
        summary_msg: Dict[str, Any] = {
            "role": "system",
            "content": [
                {
                    "type": "text",
                    "text": SUMMARY_PREFIX + summary,
                    # Anthropic-style prompt-cache hint; passthrough metadata
                    # for providers that understand it (reference v1.py:198).
                    "cache_control": {"type": "ephemeral"},
                }
            ],
        }
        rebuilt = system_msgs + [summary_msg] + kept
        out = validate_message_structure(rebuilt)
        if eff_fit is not None and not eff_fit(out):
            # summary + kept tail still over budget (huge tail messages):
            # hand the rebuilt list to token-aware truncation, preserving
            # the summary (it sits in the system prefix now)
            out = await self.fallback.compact(out, model, fit=eff_fit)
        logger.info(
            "summarization compaction: %d messages -> %d (summarized %d)",
            len(messages), len(out), split,
        )
        return out

    async def _summarize(
        self, messages: List[Dict[str, Any]], model: Optional[str]
    ) -> str:
        transcript = _render_transcript(messages)
        transcript = self._cap_transcript(transcript)
        extra: Dict[str, Any] = {}
        if getattr(self.llm, "supports_background", False):
            # ISSUE 20: the summarization call is maintenance work on the
            # serving engine — ride the background class so it never
            # convoys an interactive request's TTFT (the output is
            # byte-identical to a foreground run; only scheduling
            # priority differs).  OpenAI-shaped providers would choke on
            # the kwarg, hence the capability gate.
            extra["background"] = True
        resp = await self.llm.completion(
            [
                {"role": "system", "content": SUMMARY_SYSTEM_PROMPT},
                {
                    "role": "user",
                    "content": "Summarize this conversation:\n\n" + transcript,
                },
            ],
            model=model,
            temperature=self.temperature,
            max_tokens=self.max_summary_tokens,
            **extra,
        )
        content = resp.content or ""
        if not content.strip():
            raise RuntimeError("summarizer returned empty content")
        return content.strip()

    def _cap_transcript(self, transcript: str) -> str:
        """Shrink the transcript until the summarization request itself fits
        the summarizer's context (keeps the newest portion)."""
        probe = lambda t: [
            {"role": "system", "content": SUMMARY_SYSTEM_PROMPT},
            {"role": "user", "content": "Summarize this conversation:\n\n" + t},
        ]
        fit = self.fit if self.fit is not None else fit_from_provider(self.llm)
        if fit is None:
            return transcript
        omitted = "[earlier part of the conversation omitted]\n"
        while transcript and not fit(probe(transcript)):
            if len(transcript) <= 64:
                break  # can't shrink further; caller falls back on error
            cut = max(len(transcript) // 4, 64)
            transcript = omitted + transcript[cut:]
        return transcript


def _render_transcript(messages: List[Dict[str, Any]]) -> str:
    """Flatten messages (incl. tool calls/results) to plain text."""
    lines: List[str] = []
    for m in messages:
        role = m.get("role", "?")
        content = m.get("content")
        if isinstance(content, list):
            content = " ".join(
                p.get("text", "[image]")
                for p in content
                if isinstance(p, dict)
            )
        if content:
            lines.append(f"{role}: {content}")
        for tc in m.get("tool_calls") or []:
            fn = tc.get("function", {})
            lines.append(
                f"{role} called tool {fn.get('name')}({fn.get('arguments')})"
            )
    return "\n".join(lines)
