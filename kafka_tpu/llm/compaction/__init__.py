"""Context compaction: classifier, safe splitting, summarize/truncate ladder."""

from .base import (
    CONTEXT_LENGTH_PATTERNS,
    ContextCompactionProvider,
    find_safe_split_point,
    is_context_length_error,
    validate_message_structure,
)
from .v1 import (
    SummarizationCompactionProvider,
    TruncationCompactionProvider,
    fit_from_provider,
)

__all__ = [
    "CONTEXT_LENGTH_PATTERNS",
    "ContextCompactionProvider",
    "SummarizationCompactionProvider",
    "TruncationCompactionProvider",
    "find_safe_split_point",
    "fit_from_provider",
    "is_context_length_error",
    "validate_message_structure",
]
