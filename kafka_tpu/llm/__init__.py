"""LLM provider tier: ABC + the TPU-engine-backed provider.

The reference's provider tier proxied a remote gateway
(src/llm/portkey.py); here the provider IS the engine — requests flow into
the continuous-batching scheduler on a dispatch thread and stream back as
per-token chunks.
"""

from .base import LLMProvider, to_message_dicts
from .tpu_provider import IncrementalDetokenizer, TPULLMProvider
from .utils import infer_provider_from_model, prune_images
from .worker import EngineWorker

__all__ = [
    "EngineWorker",
    "IncrementalDetokenizer",
    "LLMProvider",
    "TPULLMProvider",
    "infer_provider_from_model",
    "prune_images",
    "to_message_dicts",
]
