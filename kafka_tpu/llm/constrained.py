"""Constrained JSON decoding for tool calls (BASELINE config 4).

The engine's sampler accepts a per-request ``logits_mask_fn`` (runtime/
engine.py); this module supplies the brain behind it: a mask that forces
generations to be exactly

    {"name": "<declared tool>", "parameters": {<schema keys>: <JSON>}}

followed by end-of-turn — so forced tool calls always parse, the name is
always a declared tool, and top-level parameter keys always come from the
tool's JSON-schema ``properties`` (free JSON is allowed inside values,
and for tools that declare no properties).

Design, sized for a 128k vocab:

* a character-level **JSON pushdown automaton** (`JsonPDA`) validates free
  value regions incrementally — strings/escapes/\\u, the full number DFA,
  literals, nested containers;
* a **template automaton** (`ToolCallAutomaton`) walks the fixed skeleton,
  a trie of tool names, a per-tool trie of parameter keys, and delegates
  value regions to the PDA.  Canonical separators (`": "`, `", "`) keep the
  skeleton deterministic;
* a per-tokenizer **TokenIndex** (built once, cached) decodes every vocab
  token and buckets ids by first character, and precomputes the
  `string_safe` id set (no quote/backslash/control bytes).  Inside free
  string content the allowed set is that precomputed array plus a handful
  of trial-checked quote/escape tokens — never a Python scan of the vocab.
  Structural positions probe the automaton for legal next characters and
  trial-feed only the matching first-char buckets.

The reference could not do any of this: its sampler lived behind a remote
HTTPS gateway (src/llm/portkey.py), so tool-call JSON was best-effort.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

WS = " \t\n\r"
DIGITS = "0123456789"
# characters probed when asking an automaton "what may come next"
PROBE_CHARS = (
    "".join(chr(c) for c in range(0x20, 0x7F)) + "\t\n\r"
)


# ---------------------------------------------------------------------------
# character-level JSON automaton
# ---------------------------------------------------------------------------


class JsonPDA:
    """Incremental validator for a single JSON value.

    `feed(ch)` returns False (and leaves state undefined) on an illegal
    character; callers trial-feed copies.  `complete` is True when exactly
    one whole value has been consumed (numbers complete implicitly, so a
    terminal-number state with an empty stack also counts via
    `would_complete`)."""

    __slots__ = ("stack", "state", "lit", "max_depth")

    # number DFA states that may legally end the number
    _NUM_TERMINAL = {"num_zero", "num_int", "num_frac", "num_exp"}

    def __init__(self, max_depth: int = 8) -> None:
        self.stack: List[str] = []
        self.state = "value"
        self.lit = ""  # remaining chars of true/false/null
        # nesting cap: keeps the worst-case "distance to a valid close"
        # bounded, which the wrap-up mode (ToolCallMaskFn) relies on
        self.max_depth = max_depth

    def copy(self) -> "JsonPDA":
        c = JsonPDA.__new__(JsonPDA)
        c.stack = list(self.stack)
        c.state = self.state
        c.lit = self.lit
        c.max_depth = self.max_depth
        return c

    # -- helpers --------------------------------------------------------

    @property
    def complete(self) -> bool:
        return not self.stack and self.state == "end"

    @property
    def would_complete(self) -> bool:
        """True if ending input here yields a complete value (covers the
        implicit termination of top-level numbers)."""
        return self.complete or (
            not self.stack and self.state in self._NUM_TERMINAL
        )

    @property
    def in_string(self) -> bool:
        """Inside free string content (escape states excluded)."""
        return self.state in ("in_str", "key_str")

    def _value_done(self) -> None:
        self.state = "end"

    # -- transitions ----------------------------------------------------

    def feed(self, ch: str) -> bool:  # noqa: C901 (a DFA is a big switch)
        s = self.state
        # number states terminate implicitly: close, then re-dispatch
        if s.startswith("num"):
            if self._feed_num(ch):
                return True
            if s in self._NUM_TERMINAL:
                self._value_done()
                return self.feed(ch)
            return False

        if s == "value":
            if ch in WS:
                return True
            if ch == '"':
                self.state = "in_str"
            elif ch == "{":
                if len(self.stack) >= self.max_depth:
                    return False
                self.stack.append("obj")
                self.state = "key_or_close"
            elif ch == "[":
                if len(self.stack) >= self.max_depth:
                    return False
                self.stack.append("arr")
                self.state = "value_or_close"
            elif ch == "-":
                self.state = "num_minus"
            elif ch == "0":
                self.state = "num_zero"
            elif ch in "123456789":
                self.state = "num_int"
            elif ch == "t":
                self.state, self.lit = "lit", "rue"
            elif ch == "f":
                self.state, self.lit = "lit", "alse"
            elif ch == "n":
                self.state, self.lit = "lit", "ull"
            else:
                return False
            return True

        if s == "lit":
            if self.lit and ch == self.lit[0]:
                self.lit = self.lit[1:]
                if not self.lit:
                    self._value_done()
                return True
            return False

        if s == "in_str":
            if ch == '"':
                self._value_done()
            elif ch == "\\":
                self.state = "str_esc"
            elif ord(ch) < 0x20:
                return False
            return True
        if s == "str_esc":
            if ch in '"\\/bfnrt':
                self.state = "in_str"
            elif ch == "u":
                self.state = "str_u0"
            else:
                return False
            return True
        if s in ("str_u0", "str_u1", "str_u2", "str_u3"):
            if ch in "0123456789abcdefABCDEF":
                self.state = (
                    "in_str" if s == "str_u3" else f"str_u{int(s[-1]) + 1}"
                )
                return True
            return False

        # object machinery
        if s == "key_or_close":
            if ch in WS:
                return True
            if ch == '"':
                self.state = "key_str"
                return True
            if ch == "}":
                self.stack.pop()
                self._value_done()
                return True
            return False
        if s == "key":
            if ch in WS:
                return True
            if ch == '"':
                self.state = "key_str"
                return True
            return False
        if s == "key_str":
            if ch == '"':
                self.state = "colon"
            elif ch == "\\":
                self.state = "key_esc"
            elif ord(ch) < 0x20:
                return False
            return True
        if s == "key_esc":
            if ch in '"\\/bfnrt':
                self.state = "key_str"
                return True
            return False
        if s == "colon":
            if ch in WS:
                return True
            if ch == ":":
                self.state = "value"
                return True
            return False

        if s == "value_or_close":
            if ch in WS:
                return True
            if ch == "]":
                self.stack.pop()
                self._value_done()
                return True
            self.state = "value"
            return self.feed(ch)

        if s == "end":
            if ch in WS:
                return True
            if self.stack:
                top = self.stack[-1]
                if ch == ",":
                    self.state = "key" if top == "obj" else "value"
                    return True
                if ch == "}" and top == "obj":
                    self.stack.pop()
                    self._value_done()
                    return True
                if ch == "]" and top == "arr":
                    self.stack.pop()
                    self._value_done()
                    return True
            return False

        return False

    def _feed_num(self, ch: str) -> bool:
        s = self.state
        if s == "num_minus":
            if ch == "0":
                self.state = "num_zero"
            elif ch in "123456789":
                self.state = "num_int"
            else:
                return False
            return True
        if s == "num_zero":
            if ch == ".":
                self.state = "num_frac_dot"
            elif ch in "eE":
                self.state = "num_exp_e"
            else:
                return False
            return True
        if s == "num_int":
            if ch in DIGITS:
                return True
            if ch == ".":
                self.state = "num_frac_dot"
            elif ch in "eE":
                self.state = "num_exp_e"
            else:
                return False
            return True
        if s == "num_frac_dot":
            if ch in DIGITS:
                self.state = "num_frac"
                return True
            return False
        if s == "num_frac":
            if ch in DIGITS:
                return True
            if ch in "eE":
                self.state = "num_exp_e"
                return True
            return False
        if s == "num_exp_e":
            if ch in "+-":
                self.state = "num_exp_sign"
                return True
            if ch in DIGITS:
                self.state = "num_exp"
                return True
            return False
        if s == "num_exp_sign":
            if ch in DIGITS:
                self.state = "num_exp"
                return True
            return False
        if s == "num_exp":
            return ch in DIGITS
        return False

    def feed_text(self, text: str) -> bool:
        for ch in text:
            if not self.feed(ch):
                return False
        return True


# ---------------------------------------------------------------------------
# trie (tool names / parameter keys)
# ---------------------------------------------------------------------------


class _Trie:
    def __init__(self, words: Iterable[str]):
        self.root: Dict[str, Any] = {}
        for w in words:
            node = self.root
            for ch in w:
                node = node.setdefault(ch, {})
            node[None] = True  # terminal marker (no char collides with None)

    def step(self, node: Dict[str, Any], ch: str) -> Optional[Dict[str, Any]]:
        return node.get(ch)

    @staticmethod
    def shortest_exit(node: Dict[str, Any]) -> str:
        """First char of a shortest path from `node` to a terminal."""
        if None in node:
            return ""  # already terminal
        best_ch, best_len = "", 1 << 30

        def depth(n: Dict[str, Any]) -> int:
            if None in n:
                return 0
            return 1 + min(depth(c) for k, c in n.items() if k is not None)

        for k, child in node.items():
            if k is None:
                continue
            d = 1 + depth(child)
            if d < best_len:
                best_len, best_ch = d, k
        return best_ch


# ---------------------------------------------------------------------------
# tool-call template automaton
# ---------------------------------------------------------------------------

_HEAD = '{"name": "'
_MID = '", "parameters": {'
_TAIL = "}"


class ToolCallAutomaton:
    """Accepts exactly the canonical tool-call JSON (module docstring).

    States:
      head:<i>        inside the literal head
      name            walking the tool-name trie
      mid:<i>         inside the literal mid section
      p_key_or_close  params object: '"' (first key) or '}' (no params)
      p_key           walking the parameter-key trie (or free string)
      p_colon:<i>     the literal '": '
      p_value         inside a free JSON value (inner JsonPDA)
      p_sep:<i>       the literal ', "' between entries
      tail:<i>        the closing literal
      done            only end-of-turn may follow
    """

    def __init__(
        self,
        tools: Sequence[Dict[str, Any]],
        force_name: Optional[str] = None,
    ):
        self._props_by_name: Dict[str, Optional[List[str]]] = {}
        names = []
        for t in tools:
            fn = t.get("function", t)
            name = fn.get("name")
            if not name:
                continue
            if force_name is not None and name != force_name:
                continue
            names.append(name)
            params = fn.get("parameters") or {}
            props = list((params.get("properties") or {}).keys())
            if params.get("additionalProperties") is True or (
                not props and "properties" not in params
            ):
                # explicitly open, or no schema at all: free-form keys
                self._props_by_name[name] = None
            else:
                # declared property set (possibly empty -> params must be {})
                self._props_by_name[name] = props
        if not names:
            raise ValueError("no tools to constrain to")
        self._name_trie = _Trie(names)
        self.reset()

    def reset(self) -> None:
        self.state: Tuple[str, Any] = ("head", 0)
        self._name_chars: List[str] = []
        self._name_node = self._name_trie.root
        self._key_trie: Optional[_Trie] = None
        self._key_node: Optional[Dict[str, Any]] = None
        self._key_pda: Optional[JsonPDA] = None  # free-key fallback
        self._value_pda: Optional[JsonPDA] = None

    def copy(self) -> "ToolCallAutomaton":
        c = ToolCallAutomaton.__new__(ToolCallAutomaton)
        c._props_by_name = self._props_by_name
        c._name_trie = self._name_trie
        c.state = self.state
        c._name_chars = list(self._name_chars)
        c._name_node = self._name_node
        c._key_trie = self._key_trie
        c._key_node = self._key_node
        c._key_pda = self._key_pda.copy() if self._key_pda else None
        c._value_pda = self._value_pda.copy() if self._value_pda else None
        return c

    @property
    def done(self) -> bool:
        return self.state[0] == "done"

    @property
    def in_free_string(self) -> bool:
        """Inside unconstrained string content (precomputed-set fast path)."""
        kind = self.state[0]
        if kind == "p_value":
            return self._value_pda is not None and self._value_pda.in_string
        if kind == "p_key" and self._key_trie is None:
            return self._key_pda is not None and self._key_pda.state == "key_str"
        return False

    # ------------------------------------------------------------------

    def _enter_params(self) -> None:
        name = "".join(self._name_chars)
        props = self._props_by_name.get(name)
        self._key_trie = _Trie(props) if props is not None else None
        self.state = ("p_key_or_close", None)

    def _start_key(self) -> None:
        if self._key_trie is not None:
            self._key_node = self._key_trie.root
        else:
            pda = JsonPDA()
            pda.state = "key_str"
            self._key_pda = pda
        self.state = ("p_key", None)

    def feed(self, ch: str) -> bool:  # noqa: C901
        kind, arg = self.state
        if kind == "head":
            if ch != _HEAD[arg]:
                return False
            self.state = ("name", None) if arg + 1 == len(_HEAD) else ("head", arg + 1)
            return True

        if kind == "name":
            if ch == '"':
                if None not in self._name_node:
                    return False
                self.state = ("mid", 1)  # the '"' consumed counts as _MID[0]
                return True
            nxt = self._name_trie.step(self._name_node, ch)
            if nxt is None:
                return False
            self._name_node = nxt
            self._name_chars.append(ch)
            return True

        if kind == "mid":
            if ch != _MID[arg]:
                return False
            if arg + 1 == len(_MID):
                self._enter_params()
            else:
                self.state = ("mid", arg + 1)
            return True

        if kind == "p_key_or_close":
            if ch == "}":
                self.state = ("tail", 0)
                return True
            if ch == '"':
                if self._key_trie is not None and not self._key_trie.root:
                    return False  # schema declares zero properties: {} only
                self._start_key()
                return True
            return False

        if kind == "p_key":
            if self._key_trie is not None:
                if ch == '"':
                    if None not in self._key_node:  # type: ignore[operator]
                        return False
                    self.state = ("p_colon", 0)
                    return True
                nxt = self._key_trie.step(self._key_node, ch)  # type: ignore[arg-type]
                if nxt is None:
                    return False
                self._key_node = nxt
                return True
            # free key: PDA string semantics
            assert self._key_pda is not None
            if not self._key_pda.feed(ch):
                return False
            if self._key_pda.state == "colon":  # closing quote consumed
                self._key_pda = None
                self.state = ("p_colon", 0)
            return True

        if kind == "p_colon":
            lit = ": "
            if ch != lit[arg]:
                return False
            if arg + 1 == len(lit):
                self._value_pda = JsonPDA()
                self.state = ("p_value", None)
            else:
                self.state = ("p_colon", arg + 1)
            return True

        if kind == "p_value":
            pda = self._value_pda
            assert pda is not None
            if pda.feed(ch):
                if pda.complete:
                    self._value_pda = None
                    self.state = ("p_after_value", None)
                return True
            # implicit value termination (numbers) on , or }
            if pda.would_complete and ch in ",}":
                self._value_pda = None
                self.state = ("p_after_value", None)
                return self.feed(ch)
            return False

        if kind == "p_after_value":
            if ch == ",":
                self.state = ("p_sep", 0)
                return True
            if ch == "}":
                self.state = ("tail", 0)
                return True
            return False

        if kind == "p_sep":
            lit = ' "'
            if ch != lit[arg]:
                return False
            if arg + 1 == len(lit):
                self._start_key()
            else:
                self.state = ("p_sep", arg + 1)
            return True

        if kind == "tail":
            if ch != _TAIL[arg]:
                return False
            if arg + 1 == len(_TAIL):
                self.state = ("done", None)
            else:
                self.state = ("tail", arg + 1)
            return True

        return False  # done: no further text

    def feed_text(self, text: str) -> bool:
        for ch in text:
            if not self.feed(ch):
                return False
        return True

    def wrap_char(self) -> Optional[str]:
        """Next char on a shortest path to `done` (wrap-up mode).

        With JsonPDA.max_depth bounding nesting, the distance from any
        reachable state to `done` is small and this greedy walk always
        terminates the call.  Returns None when done."""
        kind, arg = self.state
        if kind == "done":
            return None
        if kind == "head":
            return _HEAD[arg]
        if kind == "mid":
            return _MID[arg]
        if kind == "tail":
            return _TAIL[arg]
        if kind == "p_colon":
            return ": "[arg]
        if kind == "p_sep":
            # mid-separator: must finish it, then the shortest key
            return ' "'[arg]
        if kind == "name":
            return _Trie.shortest_exit(self._name_node) or '"'
        if kind == "p_key_or_close":
            return "}"
        if kind == "p_after_value":
            return "}"
        if kind == "p_key":
            if self._key_trie is not None:
                return _Trie.shortest_exit(self._key_node) or '"'  # type: ignore[arg-type]
            return '"'  # close the free key
        if kind == "p_value":
            pda = self._value_pda
            assert pda is not None
            s = pda.state
            if s == "value":
                return "0"  # minimal value
            if s == "in_str":
                return '"'
            if s == "str_esc":
                return "n"
            if s.startswith("str_u"):
                return "0"
            if s == "lit":
                return pda.lit[0]
            if s.startswith("num"):
                if s in JsonPDA._NUM_TERMINAL:
                    if pda.stack:
                        return "}" if pda.stack[-1] == "obj" else "]"
                    return "}"  # closes params via implicit value end
                return "0"
            if s == "key_or_close":
                return "}"
            if s == "key":
                return '"'
            if s in ("key_str",):
                return '"'
            if s == "key_esc":
                return "n"
            if s == "colon":
                return ":"
            if s == "value_or_close":
                return "]"
            if s == "end":
                if pda.stack:
                    return "}" if pda.stack[-1] == "obj" else "]"
                return "}"  # value complete -> params close via p_after_value
        return None

    def min_close_chars(self, limit: int = 512) -> int:
        """Characters on the shortest path from here to `done` (greedy walk
        of wrap_char; bounded because JsonPDA caps nesting)."""
        c = self.copy()
        n = 0
        while not c.done and n < limit:
            ch = c.wrap_char()
            if not ch:
                break
            if not c.feed(ch):  # pragma: no cover — wrap_char is always legal
                break
            n += 1
        return n


# ---------------------------------------------------------------------------
# tokenizer-level mask
# ---------------------------------------------------------------------------

_TOKEN_INDEX_LOCK = __import__("threading").Lock()


class TokenIndex:
    """Per-tokenizer vocab index for mask building (built once, cached)."""

    def __init__(self, tokenizer) -> None:
        self.vocab_size = tokenizer.vocab_size
        # tokenizers may pad their id space past the real token set
        # (ByteTokenizer.mask_vocab_size); padding ids are not grammar
        # tokens — indexing them would turn forced characters into fake
        # multi-option masks and break singleton-chained dispatch
        index_limit = min(
            self.vocab_size,
            getattr(tokenizer, "mask_vocab_size", self.vocab_size),
        )
        texts: List[str] = []
        for i in range(index_limit):
            try:
                texts.append(tokenizer.decode([i]))
            except Exception:
                texts.append("")
        texts.extend("" for _ in range(self.vocab_size - index_limit))
        self.texts = texts
        # longest decoded token: bounds forced_id's deterministic-run walk
        # (a single-char tokenizer never probes past one character)
        self.max_token_len = max((len(t) for t in texts), default=1)
        self.buckets: Dict[str, List[int]] = {}
        safe: List[int] = []
        for i, t in enumerate(texts):
            if not t or "�" in t:
                # specials / tokens that don't decode standalone (partial
                # UTF-8 byte tokens): excluded — the mask can only admit
                # text it can validate
                continue
            self.buckets.setdefault(t[0], []).append(i)
            if all(c not in '"\\' and ord(c) >= 0x20 for c in t):
                safe.append(i)
        self.string_safe = np.asarray(safe, np.int64)

    @classmethod
    def for_tokenizer(cls, tokenizer) -> "TokenIndex":
        """Cached build; the lock keeps a warmup thread and the first
        request from decoding the vocab twice (a 128k-vocab build is
        seconds of work — see TokenIndex.warm).

        The cache lives ON the tokenizer object: an id()-keyed dict can
        hand a NEW tokenizer the index of a garbage-collected one whose
        id the allocator reused (observed as a cross-test flake).
        """
        idx = getattr(tokenizer, "_token_index_cache", None)
        if idx is not None:
            return idx
        with _TOKEN_INDEX_LOCK:
            idx = getattr(tokenizer, "_token_index_cache", None)
            if idx is None:
                idx = cls(tokenizer)
                try:
                    tokenizer._token_index_cache = idx
                except Exception:
                    pass  # slotted/frozen tokenizer: rebuild per call
        return idx

    @classmethod
    def warm(cls, tokenizer) -> None:
        """Build the index off the event loop (daemon thread)."""
        import threading

        threading.Thread(
            target=cls.for_tokenizer, args=(tokenizer,), daemon=True,
            name="kafka-tpu-token-index",
        ).start()


class ToolCallMaskFn:
    """`logits_mask_fn` forcing canonical tool-call JSON (engine protocol:
    called with output_ids, returns allowed token ids or None)."""

    # extra tokens kept in hand beyond the computed shortest-close distance
    # (each close char needs at most one token)
    WRAP_UP_SLACK = 4

    def __init__(
        self,
        tokenizer,
        tools: Sequence[Dict[str, Any]],
        force_name: Optional[str] = None,
        max_tokens: Optional[int] = None,
    ):
        self._tok = tokenizer
        self._index = TokenIndex.for_tokenizer(tokenizer)
        self._auto = ToolCallAutomaton(tools, force_name=force_name)
        self._consumed = 0  # output_ids already fed (incremental)
        self._fed_text_len = 0
        self._max_tokens = max_tokens
        # (text position, remaining deterministic run) memo: consecutive
        # forced_id calls slice the already-derived run instead of
        # re-probing ~98 chars per position (scheduler hot path)
        self._run_cache: Tuple[int, str] = (-1, "")

    def set_budget(self, max_tokens: int) -> None:
        """Engine hook: the token budget after window clamping.  Near its
        end the mask restricts to a shortest valid close (wrap-up), so a
        bounded generation still parses."""
        self._max_tokens = max_tokens

    def _sync(self, output_ids: List[int]) -> bool:
        """Advance the automaton to the given prefix (incremental).
        Returns False when the prefix stopped validating (degrade)."""
        if self._consumed > len(output_ids):  # new attempt/rewind
            self._auto.reset()
            self._consumed = 0
            self._fed_text_len = 0
        text = self._tok.decode(output_ids)
        delta = text[self._fed_text_len :]
        if delta:
            # generation is mask-constrained, so the delta always feeds
            if not self._auto.feed_text(delta):
                # defensive: unconstrained prefix (shouldn't happen) —
                # give up and stop constraining
                return False
            self._fed_text_len = len(text)
        self._consumed = len(output_ids)
        return True

    def _wrapping_up(self, output_ids: List[int]) -> bool:
        if self._max_tokens is None or self._auto.done:
            return False
        remaining = self._max_tokens - len(output_ids)
        return remaining <= self._auto.min_close_chars() + self.WRAP_UP_SLACK

    def __call__(self, output_ids: List[int]) -> Optional[List[int]]:
        if not self._sync(output_ids):
            return None
        if self._wrapping_up(output_ids):
            wrapped = self._wrap_up_ids()
            if wrapped:
                return wrapped
        return self._allowed_ids()

    # how far ahead a deterministic text run is grown for forced_id; the
    # canonical token picked is at most this many characters
    MAX_FORCED_RUN = 24

    def forced_id(self, output_ids: List[int]) -> Optional[int]:
        """Engine chaining hook: a single canonical token id when the
        grammar's next TEXT is deterministic, else None.

        With subword tokenizers a forced text region ("name", '": "', key
        names) admits many tokenizations, so the allowed-id mask is rarely
        a singleton even though the model has no actual choice; the host
        would then await a device round trip per token for nothing.  Here
        the deterministic character run is grown from the automaton and
        the LONGEST indexed token that prefixes it is returned — the
        engine dispatches it without awaiting the previous fetch, and the
        sampled token is overridden device-side.  Free-string content,
        genuine choice points, and wrap-up mode return None (the masked
        path decides).  For single-char tokenizers this returns exactly
        the singleton the mask would have allowed.
        """
        if not self._sync(output_ids):
            return None
        auto = self._auto
        if auto.done or auto.in_free_string:
            return None
        if self._wrapping_up(output_ids):
            return None
        cached_pos, cached_run = self._run_cache
        if cached_pos == self._fed_text_len and cached_run:
            run = cached_run
        else:
            c = auto.copy()
            run = ""
            limit = min(self.MAX_FORCED_RUN, self._index.max_token_len)
            while len(run) < limit and not c.done:
                legal: List[str] = []
                for ch in PROBE_CHARS:
                    if c.copy().feed(ch):
                        legal.append(ch)
                        if len(legal) > 1:
                            break  # choice point: no need to finish
                if len(legal) != 1:
                    break
                run += legal[0]
                c.feed(legal[0])
            if not run:
                return None
        best = None
        best_len = 0
        for tid in self._index.buckets.get(run[0], ()):
            t = self._index.texts[tid]
            if best_len < len(t) <= len(run) and run.startswith(t):
                best, best_len = tid, len(t)
        if best is not None:
            self._run_cache = (
                self._fed_text_len + best_len, run[best_len:]
            )
        return best

    def _allowed_ids(self) -> List[int]:
        auto, idx = self._auto, self._index
        if auto.done:
            return [self._tok.eot_id]
        allowed: List[int]
        if auto.in_free_string:
            # fast path: precomputed safe set + trial-checked specials
            allowed = list(idx.string_safe)
            for ch in ('"', "\\"):
                for tid in idx.buckets.get(ch, ()):
                    if self._trial(tid):
                        allowed.append(tid)
            return allowed
        legal = [ch for ch in PROBE_CHARS if auto.copy().feed(ch)]
        allowed = []
        for ch in legal:
            for tid in idx.buckets.get(ch, ()):
                if self._trial(tid):
                    allowed.append(tid)
        if auto.done:  # pragma: no cover (handled above)
            allowed.append(self._tok.eot_id)
        return allowed

    def _wrap_up_ids(self) -> List[int]:
        """Allowed ids in wrap-up mode: tokens starting with the shortest
        path-to-close character that validate fully."""
        ch = self._auto.wrap_char()
        if ch is None or ch == "":
            return [self._tok.eot_id]
        out = [
            tid
            for tid in self._index.buckets.get(ch, ())
            if self._trial(tid)
        ]
        return out

    def _trial(self, token_id: int) -> bool:
        text = self._index.texts[token_id]
        c = self._auto.copy()
        for ch in text:
            if c.done:
                return False  # text runs past the end of the call
            if not c.feed(ch):
                return False
        return True


def build_tool_call_mask_fn(
    tokenizer,
    tools: Sequence[Dict[str, Any]],
    tool_choice: Any = "required",
) -> Optional[ToolCallMaskFn]:
    """Resolve an OpenAI-style tool_choice into a mask fn (None = don't).

    Only "required" and {"type": "function", "function": {"name": ...}}
    constrain; "auto"/"none"/None and unrecognized values return None.  A
    forced name that matches no declared tool degrades to unconstrained
    with a warning rather than failing the request.
    """
    if not tools:
        return None
    force = None
    if isinstance(tool_choice, dict):
        force = (tool_choice.get("function") or {}).get("name")
        declared = {
            (t.get("function", t)).get("name") for t in tools
        }
        if force not in declared:
            import logging

            logging.getLogger("kafka_tpu.constrained").warning(
                "tool_choice forces unknown function %r (declared: %s); "
                "falling back to unconstrained generation",
                force, sorted(n for n in declared if n),
            )
            return None
    elif tool_choice != "required":
        return None
    return ToolCallMaskFn(tokenizer, tools, force_name=force)


def validate_tool_call_json(
    text: str, tools: Sequence[Dict[str, Any]]
) -> bool:
    """Post-hoc check used by tests: parses, names a declared tool, and
    top-level parameter keys are declared properties."""
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        return False
    if not isinstance(obj, dict):
        return False
    by_name = {}
    for t in tools:
        fn = t.get("function", t)
        by_name[fn.get("name")] = fn.get("parameters") or {}
    if obj.get("name") not in by_name:
        return False
    params = obj.get("parameters")
    if not isinstance(params, dict):
        return False
    schema = by_name[obj["name"]]
    props = (schema.get("properties") or {}).keys()
    if props and schema.get("additionalProperties") is not True:
        return all(k in props for k in params)
    return True
