"""Constrained JSON decoding for tool calls (BASELINE config 4).

The engine's sampler accepts a per-request ``logits_mask_fn`` (runtime/
engine.py); this module supplies the brain behind it: a mask that forces
generations to be exactly

    {"name": "<declared tool>", "parameters": {<schema keys>: <JSON>}}

followed by end-of-turn — so forced tool calls always parse, the name is
always a declared tool, and top-level parameter keys always come from the
tool's JSON-schema ``properties`` (free JSON is allowed inside values,
and for tools that declare no properties).

Design, sized for a 128k vocab:

* a character-level **JSON pushdown automaton** (`JsonPDA`) validates free
  value regions incrementally — strings/escapes/\\u, the full number DFA,
  literals, nested containers;
* a **template automaton** (`ToolCallAutomaton`) walks the fixed skeleton,
  a trie of tool names, a per-tool trie of parameter keys, and delegates
  value regions to the PDA.  Canonical separators (`": "`, `", "`) keep the
  skeleton deterministic;
* a per-tokenizer **TokenIndex** (built once, cached) decodes every vocab
  token and buckets ids by first character, and precomputes the
  `string_safe` id set (no quote/backslash/control bytes).  Inside free
  string content the allowed set is that precomputed array plus a handful
  of trial-checked quote/escape tokens — never a Python scan of the vocab.
  Structural positions probe the automaton for legal next characters and
  trial-feed only the matching first-char buckets.

The reference could not do any of this: its sampler lived behind a remote
HTTPS gateway (src/llm/portkey.py), so tool-call JSON was best-effort.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

WS = " \t\n\r"
DIGITS = "0123456789"
# characters probed when asking an automaton "what may come next"
PROBE_CHARS = (
    "".join(chr(c) for c in range(0x20, 0x7F)) + "\t\n\r"
)


# ---------------------------------------------------------------------------
# character-level JSON automaton
# ---------------------------------------------------------------------------


class JsonPDA:
    """Incremental validator for a single JSON value.

    `feed(ch)` returns False (and leaves state undefined) on an illegal
    character; callers trial-feed copies.  `complete` is True when exactly
    one whole value has been consumed (numbers complete implicitly, so a
    terminal-number state with an empty stack also counts via
    `would_complete`)."""

    __slots__ = ("stack", "state", "lit", "max_depth")

    # number DFA states that may legally end the number
    _NUM_TERMINAL = {"num_zero", "num_int", "num_frac", "num_exp"}

    def __init__(self, max_depth: int = 8) -> None:
        self.stack: List[str] = []
        self.state = "value"
        self.lit = ""  # remaining chars of true/false/null
        # nesting cap: keeps the worst-case "distance to a valid close"
        # bounded, which the wrap-up mode (ToolCallMaskFn) relies on
        self.max_depth = max_depth

    def copy(self) -> "JsonPDA":
        c = JsonPDA.__new__(JsonPDA)
        c.stack = list(self.stack)
        c.state = self.state
        c.lit = self.lit
        c.max_depth = self.max_depth
        return c

    # -- helpers --------------------------------------------------------

    @property
    def complete(self) -> bool:
        return not self.stack and self.state == "end"

    @property
    def would_complete(self) -> bool:
        """True if ending input here yields a complete value (covers the
        implicit termination of top-level numbers)."""
        return self.complete or (
            not self.stack and self.state in self._NUM_TERMINAL
        )

    @property
    def in_string(self) -> bool:
        """Inside free string content (escape states excluded)."""
        return self.state in ("in_str", "key_str")

    def _value_done(self) -> None:
        self.state = "end"

    # -- transitions ----------------------------------------------------

    def feed(self, ch: str) -> bool:  # noqa: C901 (a DFA is a big switch)
        s = self.state
        # number states terminate implicitly: close, then re-dispatch
        if s.startswith("num"):
            if self._feed_num(ch):
                return True
            if s in self._NUM_TERMINAL:
                self._value_done()
                return self.feed(ch)
            return False

        if s == "value":
            if ch in WS:
                return True
            if ch == '"':
                self.state = "in_str"
            elif ch == "{":
                if len(self.stack) >= self.max_depth:
                    return False
                self.stack.append("obj")
                self.state = "key_or_close"
            elif ch == "[":
                if len(self.stack) >= self.max_depth:
                    return False
                self.stack.append("arr")
                self.state = "value_or_close"
            elif ch == "-":
                self.state = "num_minus"
            elif ch == "0":
                self.state = "num_zero"
            elif ch in "123456789":
                self.state = "num_int"
            elif ch == "t":
                self.state, self.lit = "lit", "rue"
            elif ch == "f":
                self.state, self.lit = "lit", "alse"
            elif ch == "n":
                self.state, self.lit = "lit", "ull"
            else:
                return False
            return True

        if s == "lit":
            if self.lit and ch == self.lit[0]:
                self.lit = self.lit[1:]
                if not self.lit:
                    self._value_done()
                return True
            return False

        if s == "in_str":
            if ch == '"':
                self._value_done()
            elif ch == "\\":
                self.state = "str_esc"
            elif ord(ch) < 0x20:
                return False
            return True
        if s == "str_esc":
            if ch in '"\\/bfnrt':
                self.state = "in_str"
            elif ch == "u":
                self.state = "str_u0"
            else:
                return False
            return True
        if s in ("str_u0", "str_u1", "str_u2", "str_u3"):
            if ch in "0123456789abcdefABCDEF":
                self.state = (
                    "in_str" if s == "str_u3" else f"str_u{int(s[-1]) + 1}"
                )
                return True
            return False

        # object machinery
        if s == "key_or_close":
            if ch in WS:
                return True
            if ch == '"':
                self.state = "key_str"
                return True
            if ch == "}":
                self.stack.pop()
                self._value_done()
                return True
            return False
        if s == "key":
            if ch in WS:
                return True
            if ch == '"':
                self.state = "key_str"
                return True
            return False
        if s == "key_str":
            if ch == '"':
                self.state = "colon"
            elif ch == "\\":
                self.state = "key_esc"
            elif ord(ch) < 0x20:
                return False
            return True
        if s == "key_esc":
            if ch in '"\\/bfnrt':
                self.state = "key_str"
                return True
            return False
        if s == "colon":
            if ch in WS:
                return True
            if ch == ":":
                self.state = "value"
                return True
            return False

        if s == "value_or_close":
            if ch in WS:
                return True
            if ch == "]":
                self.stack.pop()
                self._value_done()
                return True
            self.state = "value"
            return self.feed(ch)

        if s == "end":
            if ch in WS:
                return True
            if self.stack:
                top = self.stack[-1]
                if ch == ",":
                    self.state = "key" if top == "obj" else "value"
                    return True
                if ch == "}" and top == "obj":
                    self.stack.pop()
                    self._value_done()
                    return True
                if ch == "]" and top == "arr":
                    self.stack.pop()
                    self._value_done()
                    return True
            return False

        return False

    def _feed_num(self, ch: str) -> bool:
        s = self.state
        if s == "num_minus":
            if ch == "0":
                self.state = "num_zero"
            elif ch in "123456789":
                self.state = "num_int"
            else:
                return False
            return True
        if s == "num_zero":
            if ch == ".":
                self.state = "num_frac_dot"
            elif ch in "eE":
                self.state = "num_exp_e"
            else:
                return False
            return True
        if s == "num_int":
            if ch in DIGITS:
                return True
            if ch == ".":
                self.state = "num_frac_dot"
            elif ch in "eE":
                self.state = "num_exp_e"
            else:
                return False
            return True
        if s == "num_frac_dot":
            if ch in DIGITS:
                self.state = "num_frac"
                return True
            return False
        if s == "num_frac":
            if ch in DIGITS:
                return True
            if ch in "eE":
                self.state = "num_exp_e"
                return True
            return False
        if s == "num_exp_e":
            if ch in "+-":
                self.state = "num_exp_sign"
                return True
            if ch in DIGITS:
                self.state = "num_exp"
                return True
            return False
        if s == "num_exp_sign":
            if ch in DIGITS:
                self.state = "num_exp"
                return True
            return False
        if s == "num_exp":
            return ch in DIGITS
        return False

    def feed_text(self, text: str) -> bool:
        for ch in text:
            if not self.feed(ch):
                return False
        return True


# ---------------------------------------------------------------------------
# trie (tool names / parameter keys)
# ---------------------------------------------------------------------------


class _Trie:
    def __init__(self, words: Iterable[str]):
        self.root: Dict[str, Any] = {}
        for w in words:
            node = self.root
            for ch in w:
                node = node.setdefault(ch, {})
            node[None] = True  # terminal marker (no char collides with None)

    def step(self, node: Dict[str, Any], ch: str) -> Optional[Dict[str, Any]]:
        return node.get(ch)

    @staticmethod
    def shortest_exit(node: Dict[str, Any]) -> str:
        """First char of a shortest path from `node` to a terminal."""
        if None in node:
            return ""  # already terminal
        best_ch, best_len = "", 1 << 30

        def depth(n: Dict[str, Any]) -> int:
            if None in n:
                return 0
            return 1 + min(depth(c) for k, c in n.items() if k is not None)

        for k, child in node.items():
            if k is None:
                continue
            d = 1 + depth(child)
            if d < best_len:
                best_len, best_ch = d, k
        return best_ch


# ---------------------------------------------------------------------------
# tool-call template automaton
# ---------------------------------------------------------------------------

_HEAD = '{"name": "'
_MID = '", "parameters": {'
_TAIL = "}"


class ToolCallAutomaton:
    """Accepts exactly the canonical tool-call JSON (module docstring).

    States:
      head:<i>        inside the literal head
      name            walking the tool-name trie
      mid:<i>         inside the literal mid section
      p_key_or_close  params object: '"' (first key) or '}' (no params)
      p_key           walking the parameter-key trie (or free string)
      p_colon:<i>     the literal '": '
      p_value         inside a free JSON value (inner JsonPDA)
      p_sep:<i>       the literal ', "' between entries
      tail:<i>        the closing literal
      done            only end-of-turn may follow
    """

    # Nesting cap for free JSON parameter VALUES (JsonPDA.max_depth).
    # Shared by the host mask path and the compiled on-device FSM — the
    # two must accept the SAME language or their token streams diverge.
    # Each extra level doubles the compiled automaton's stack alphabet
    # (2^depth stack shapes), so the cap is also what keeps the
    # grammar->table compile small; 4 levels is ample for tool arguments.
    MAX_VALUE_DEPTH = 4

    def __init__(
        self,
        tools: Sequence[Dict[str, Any]],
        force_name: Optional[str] = None,
        max_value_depth: Optional[int] = None,
    ):
        self._props_by_name: Dict[str, Optional[List[str]]] = {}
        self._value_depth = (
            max_value_depth if max_value_depth is not None
            else self.MAX_VALUE_DEPTH
        )
        names = []
        for t in tools:
            fn = t.get("function", t)
            name = fn.get("name")
            if not name:
                continue
            if force_name is not None and name != force_name:
                continue
            names.append(name)
            params = fn.get("parameters") or {}
            props = list((params.get("properties") or {}).keys())
            if params.get("additionalProperties") is True or (
                not props and "properties" not in params
            ):
                # explicitly open, or no schema at all: free-form keys
                self._props_by_name[name] = None
            else:
                # declared property set (possibly empty -> params must be {})
                self._props_by_name[name] = props
        if not names:
            raise ValueError("no tools to constrain to")
        self._name_trie = _Trie(names)
        # key tries are built ONCE per tool and shared across copies so
        # that automaton-state signatures (the grammar compiler's dedup
        # key) can use trie-node identity
        self._key_tries: Dict[str, Optional[_Trie]] = {
            name: (_Trie(props) if props is not None else None)
            for name, props in self._props_by_name.items()
        }
        self.reset()

    def reset(self) -> None:
        self.state: Tuple[str, Any] = ("head", 0)
        self._name_chars: List[str] = []
        self._name_node = self._name_trie.root
        self._key_trie: Optional[_Trie] = None
        self._key_node: Optional[Dict[str, Any]] = None
        self._key_pda: Optional[JsonPDA] = None  # free-key fallback
        self._value_pda: Optional[JsonPDA] = None

    def copy(self) -> "ToolCallAutomaton":
        c = ToolCallAutomaton.__new__(ToolCallAutomaton)
        c._props_by_name = self._props_by_name
        c._name_trie = self._name_trie
        c._key_tries = self._key_tries
        c._value_depth = self._value_depth
        c.state = self.state
        c._name_chars = list(self._name_chars)
        c._name_node = self._name_node
        c._key_trie = self._key_trie
        c._key_node = self._key_node
        c._key_pda = self._key_pda.copy() if self._key_pda else None
        c._value_pda = self._value_pda.copy() if self._value_pda else None
        return c

    @property
    def done(self) -> bool:
        return self.state[0] == "done"

    def signature(self) -> Tuple:
        """Hashable identity of this automaton state (the grammar->table
        compiler's BFS dedup key).  Trie nodes are shared dicts (one node
        per unique prefix), so their id() is a sound state component;
        the PDAs contribute (stack, state, lit)."""
        def pda_sig(p: Optional[JsonPDA]):
            return None if p is None else (tuple(p.stack), p.state, p.lit)

        return (
            self.state,
            id(self._name_node),
            id(self._key_trie) if self._key_trie is not None else None,
            id(self._key_node) if self._key_node is not None else None,
            pda_sig(self._key_pda),
            pda_sig(self._value_pda),
        )

    @property
    def in_free_string(self) -> bool:
        """Inside unconstrained string content (precomputed-set fast path)."""
        kind = self.state[0]
        if kind == "p_value":
            return self._value_pda is not None and self._value_pda.in_string
        if kind == "p_key" and self._key_trie is None:
            return self._key_pda is not None and self._key_pda.state == "key_str"
        return False

    # ------------------------------------------------------------------

    def _enter_params(self) -> None:
        name = "".join(self._name_chars)
        self._key_trie = self._key_tries.get(name)
        self.state = ("p_key_or_close", None)

    def _start_key(self) -> None:
        if self._key_trie is not None:
            self._key_node = self._key_trie.root
        else:
            pda = JsonPDA()
            pda.state = "key_str"
            self._key_pda = pda
        self.state = ("p_key", None)

    def feed(self, ch: str) -> bool:  # noqa: C901
        kind, arg = self.state
        if kind == "head":
            if ch != _HEAD[arg]:
                return False
            self.state = ("name", None) if arg + 1 == len(_HEAD) else ("head", arg + 1)
            return True

        if kind == "name":
            if ch == '"':
                if None not in self._name_node:
                    return False
                self.state = ("mid", 1)  # the '"' consumed counts as _MID[0]
                return True
            nxt = self._name_trie.step(self._name_node, ch)
            if nxt is None:
                return False
            self._name_node = nxt
            self._name_chars.append(ch)
            return True

        if kind == "mid":
            if ch != _MID[arg]:
                return False
            if arg + 1 == len(_MID):
                self._enter_params()
            else:
                self.state = ("mid", arg + 1)
            return True

        if kind == "p_key_or_close":
            if ch == "}":
                self.state = ("tail", 0)
                return True
            if ch == '"':
                if self._key_trie is not None and not self._key_trie.root:
                    return False  # schema declares zero properties: {} only
                self._start_key()
                return True
            return False

        if kind == "p_key":
            if self._key_trie is not None:
                if ch == '"':
                    if None not in self._key_node:  # type: ignore[operator]
                        return False
                    self.state = ("p_colon", 0)
                    return True
                nxt = self._key_trie.step(self._key_node, ch)  # type: ignore[arg-type]
                if nxt is None:
                    return False
                self._key_node = nxt
                return True
            # free key: PDA string semantics
            assert self._key_pda is not None
            if not self._key_pda.feed(ch):
                return False
            if self._key_pda.state == "colon":  # closing quote consumed
                self._key_pda = None
                self.state = ("p_colon", 0)
            return True

        if kind == "p_colon":
            lit = ": "
            if ch != lit[arg]:
                return False
            if arg + 1 == len(lit):
                self._value_pda = JsonPDA(max_depth=self._value_depth)
                self.state = ("p_value", None)
            else:
                self.state = ("p_colon", arg + 1)
            return True

        if kind == "p_value":
            pda = self._value_pda
            assert pda is not None
            if pda.feed(ch):
                if pda.complete:
                    self._value_pda = None
                    self.state = ("p_after_value", None)
                return True
            # implicit value termination (numbers) on , or }
            if pda.would_complete and ch in ",}":
                self._value_pda = None
                self.state = ("p_after_value", None)
                return self.feed(ch)
            return False

        if kind == "p_after_value":
            if ch == ",":
                self.state = ("p_sep", 0)
                return True
            if ch == "}":
                self.state = ("tail", 0)
                return True
            return False

        if kind == "p_sep":
            lit = ' "'
            if ch != lit[arg]:
                return False
            if arg + 1 == len(lit):
                self._start_key()
            else:
                self.state = ("p_sep", arg + 1)
            return True

        if kind == "tail":
            if ch != _TAIL[arg]:
                return False
            if arg + 1 == len(_TAIL):
                self.state = ("done", None)
            else:
                self.state = ("tail", arg + 1)
            return True

        return False  # done: no further text

    def feed_text(self, text: str) -> bool:
        for ch in text:
            if not self.feed(ch):
                return False
        return True

    def wrap_char(self) -> Optional[str]:
        """Next char on a shortest path to `done` (wrap-up mode).

        With JsonPDA.max_depth bounding nesting, the distance from any
        reachable state to `done` is small and this greedy walk always
        terminates the call.  Returns None when done."""
        kind, arg = self.state
        if kind == "done":
            return None
        if kind == "head":
            return _HEAD[arg]
        if kind == "mid":
            return _MID[arg]
        if kind == "tail":
            return _TAIL[arg]
        if kind == "p_colon":
            return ": "[arg]
        if kind == "p_sep":
            # mid-separator: must finish it, then the shortest key
            return ' "'[arg]
        if kind == "name":
            return _Trie.shortest_exit(self._name_node) or '"'
        if kind == "p_key_or_close":
            return "}"
        if kind == "p_after_value":
            return "}"
        if kind == "p_key":
            if self._key_trie is not None:
                return _Trie.shortest_exit(self._key_node) or '"'  # type: ignore[arg-type]
            return '"'  # close the free key
        if kind == "p_value":
            pda = self._value_pda
            assert pda is not None
            s = pda.state
            if s == "value":
                return "0"  # minimal value
            if s == "in_str":
                return '"'
            if s == "str_esc":
                return "n"
            if s.startswith("str_u"):
                return "0"
            if s == "lit":
                return pda.lit[0]
            if s.startswith("num"):
                if s in JsonPDA._NUM_TERMINAL:
                    if pda.stack:
                        return "}" if pda.stack[-1] == "obj" else "]"
                    return "}"  # closes params via implicit value end
                return "0"
            if s == "key_or_close":
                return "}"
            if s == "key":
                return '"'
            if s in ("key_str",):
                return '"'
            if s == "key_esc":
                return "n"
            if s == "colon":
                return ":"
            if s == "value_or_close":
                return "]"
            if s == "end":
                if pda.stack:
                    return "}" if pda.stack[-1] == "obj" else "]"
                return "}"  # value complete -> params close via p_after_value
        return None

    def min_close_chars(self, limit: int = 512) -> int:
        """Characters on the shortest path from here to `done` (greedy walk
        of wrap_char; bounded because JsonPDA caps nesting)."""
        c = self.copy()
        n = 0
        while not c.done and n < limit:
            ch = c.wrap_char()
            if not ch:
                break
            if not c.feed(ch):  # pragma: no cover — wrap_char is always legal
                break
            n += 1
        return n


# ---------------------------------------------------------------------------
# tokenizer-level mask
# ---------------------------------------------------------------------------

_TOKEN_INDEX_LOCK = __import__("threading").Lock()


class TokenIndex:
    """Per-tokenizer vocab index for mask building (built once, cached)."""

    def __init__(self, tokenizer) -> None:
        self.vocab_size = tokenizer.vocab_size
        # tokenizers may pad their id space past the real token set
        # (ByteTokenizer.mask_vocab_size); padding ids are not grammar
        # tokens — indexing them would turn forced characters into fake
        # multi-option masks and break singleton-chained dispatch
        index_limit = min(
            self.vocab_size,
            getattr(tokenizer, "mask_vocab_size", self.vocab_size),
        )
        texts: List[str] = []
        for i in range(index_limit):
            try:
                texts.append(tokenizer.decode([i]))
            except Exception:
                texts.append("")
        texts.extend("" for _ in range(self.vocab_size - index_limit))
        self.texts = texts
        # longest decoded token: bounds forced_id's deterministic-run walk
        # (a single-char tokenizer never probes past one character)
        self.max_token_len = max((len(t) for t in texts), default=1)
        self.buckets: Dict[str, List[int]] = {}
        safe: List[int] = []
        for i, t in enumerate(texts):
            if not t or "�" in t:
                # specials / tokens that don't decode standalone (partial
                # UTF-8 byte tokens): excluded — the mask can only admit
                # text it can validate
                continue
            self.buckets.setdefault(t[0], []).append(i)
            if all(c not in '"\\' and ord(c) >= 0x20 for c in t):
                safe.append(i)
        self.string_safe = np.asarray(safe, np.int64)

    @classmethod
    def for_tokenizer(cls, tokenizer) -> "TokenIndex":
        """Cached build; the lock keeps a warmup thread and the first
        request from decoding the vocab twice (a 128k-vocab build is
        seconds of work — see TokenIndex.warm).

        The cache lives ON the tokenizer object: an id()-keyed dict can
        hand a NEW tokenizer the index of a garbage-collected one whose
        id the allocator reused (observed as a cross-test flake).
        """
        idx = getattr(tokenizer, "_token_index_cache", None)
        if idx is not None:
            return idx
        with _TOKEN_INDEX_LOCK:
            idx = getattr(tokenizer, "_token_index_cache", None)
            if idx is None:
                idx = cls(tokenizer)
                try:
                    tokenizer._token_index_cache = idx
                except Exception:
                    pass  # slotted/frozen tokenizer: rebuild per call
        return idx

    @classmethod
    def warm(cls, tokenizer) -> None:
        """Build the index off the event loop (daemon thread)."""
        import threading

        threading.Thread(
            target=cls.for_tokenizer, args=(tokenizer,), daemon=True,
            name="kafka-tpu-token-index",
        ).start()


def _token_ok(auto: ToolCallAutomaton, text: str) -> bool:
    """Does the whole decoded token validate from this automaton state?
    (Runs PAST `done` are rejected — a token may end the call, never
    overshoot it.)"""
    c = auto.copy()
    for ch in text:
        if c.done:
            return False
        if not c.feed(ch):
            return False
    return True


def allowed_ids_for(
    auto: ToolCallAutomaton, index: TokenIndex, eot_id: int
) -> List[int]:
    """Token ids legal from `auto`'s state — THE mask semantics.

    Shared verbatim by the host mask path (ToolCallMaskFn._allowed_ids)
    and the grammar->table compiler (compile_tool_call_grammar), so the
    on-device FSM admits exactly the host path's token sets and the two
    paths emit bit-identical greedy streams.
    """
    if auto.done:
        return [eot_id]
    allowed: List[int]
    if auto.in_free_string:
        # fast path: precomputed safe set + trial-checked specials
        allowed = [int(t) for t in index.string_safe]
        for ch in ('"', "\\"):
            for tid in index.buckets.get(ch, ()):
                if _token_ok(auto, index.texts[tid]):
                    allowed.append(tid)
        return allowed
    legal = [ch for ch in PROBE_CHARS if auto.copy().feed(ch)]
    allowed = []
    for ch in legal:
        for tid in index.buckets.get(ch, ()):
            if _token_ok(auto, index.texts[tid]):
                allowed.append(tid)
    return allowed


class ToolCallMaskFn:
    """`logits_mask_fn` forcing canonical tool-call JSON (engine protocol:
    called with output_ids, returns allowed token ids or None)."""

    # extra tokens kept in hand beyond the computed shortest-close distance
    # (each close char needs at most one token)
    WRAP_UP_SLACK = 4

    def __init__(
        self,
        tokenizer,
        tools: Sequence[Dict[str, Any]],
        force_name: Optional[str] = None,
        max_tokens: Optional[int] = None,
    ):
        self._tok = tokenizer
        self._index = TokenIndex.for_tokenizer(tokenizer)
        self._auto = ToolCallAutomaton(tools, force_name=force_name)
        # kept for the on-device grammar compiler (compile_grammar_for_mask_fn)
        self.tools = list(tools)
        self.force_name = force_name
        self._consumed = 0  # output_ids already fed (incremental)
        self._fed_text_len = 0
        self._max_tokens = max_tokens
        # (text position, remaining deterministic run) memo: consecutive
        # forced_id calls slice the already-derived run instead of
        # re-probing ~98 chars per position (scheduler hot path)
        self._run_cache: Tuple[int, str] = (-1, "")

    def set_budget(self, max_tokens: int) -> None:
        """Engine hook: the token budget after window clamping.  Near its
        end the mask restricts to a shortest valid close (wrap-up), so a
        bounded generation still parses."""
        self._max_tokens = max_tokens

    def _sync(self, output_ids: List[int]) -> bool:
        """Advance the automaton to the given prefix (incremental).
        Returns False when the prefix stopped validating (degrade)."""
        if self._consumed > len(output_ids):  # new attempt/rewind
            self._auto.reset()
            self._consumed = 0
            self._fed_text_len = 0
        text = self._tok.decode(output_ids)
        delta = text[self._fed_text_len :]
        if delta:
            # generation is mask-constrained, so the delta always feeds
            if not self._auto.feed_text(delta):
                # defensive: unconstrained prefix (shouldn't happen) —
                # give up and stop constraining
                return False
            self._fed_text_len = len(text)
        self._consumed = len(output_ids)
        return True

    def _wrapping_up(self, output_ids: List[int]) -> bool:
        if self._max_tokens is None or self._auto.done:
            return False
        remaining = self._max_tokens - len(output_ids)
        return remaining <= self._auto.min_close_chars() + self.WRAP_UP_SLACK

    def __call__(self, output_ids: List[int]) -> Optional[List[int]]:
        if not self._sync(output_ids):
            return None
        if self._wrapping_up(output_ids):
            wrapped = self._wrap_up_ids()
            if wrapped:
                return wrapped
        return self._allowed_ids()

    # how far ahead a deterministic text run is grown for forced_id; the
    # canonical token picked is at most this many characters
    MAX_FORCED_RUN = 24

    def forced_id(self, output_ids: List[int]) -> Optional[int]:
        """Engine chaining hook: a single canonical token id when the
        grammar's next TEXT is deterministic, else None.

        With subword tokenizers a forced text region ("name", '": "', key
        names) admits many tokenizations, so the allowed-id mask is rarely
        a singleton even though the model has no actual choice; the host
        would then await a device round trip per token for nothing.  Here
        the deterministic character run is grown from the automaton and
        the LONGEST indexed token that prefixes it is returned — the
        engine dispatches it without awaiting the previous fetch, and the
        sampled token is overridden device-side.  Free-string content,
        genuine choice points, and wrap-up mode return None (the masked
        path decides).  For single-char tokenizers this returns exactly
        the singleton the mask would have allowed.
        """
        if not self._sync(output_ids):
            return None
        auto = self._auto
        if auto.done or auto.in_free_string:
            return None
        if self._wrapping_up(output_ids):
            return None
        cached_pos, cached_run = self._run_cache
        if cached_pos == self._fed_text_len and cached_run:
            run = cached_run
        else:
            c = auto.copy()
            run = ""
            limit = min(self.MAX_FORCED_RUN, self._index.max_token_len)
            while len(run) < limit and not c.done:
                legal: List[str] = []
                for ch in PROBE_CHARS:
                    if c.copy().feed(ch):
                        legal.append(ch)
                        if len(legal) > 1:
                            break  # choice point: no need to finish
                if len(legal) != 1:
                    break
                run += legal[0]
                c.feed(legal[0])
            if not run:
                return None
        best = None
        best_len = 0
        for tid in self._index.buckets.get(run[0], ()):
            t = self._index.texts[tid]
            if best_len < len(t) <= len(run) and run.startswith(t):
                best, best_len = tid, len(t)
        if best is not None:
            self._run_cache = (
                self._fed_text_len + best_len, run[best_len:]
            )
        return best

    def _allowed_ids(self) -> List[int]:
        return allowed_ids_for(self._auto, self._index, self._tok.eot_id)

    def state_desc(self) -> str:
        """Human-readable automaton state (over-tight-mask log lines)."""
        return repr(self._auto.state)

    def _wrap_up_ids(self) -> List[int]:
        """Allowed ids in wrap-up mode: tokens starting with the shortest
        path-to-close character that validate fully."""
        ch = self._auto.wrap_char()
        if ch is None or ch == "":
            return [self._tok.eot_id]
        out = [
            tid
            for tid in self._index.buckets.get(ch, ())
            if self._trial(tid)
        ]
        return out

    def _trial(self, token_id: int) -> bool:
        # same semantics as the compiler's trial feed — the host/device
        # mask-equality guarantee rests on sharing ONE implementation
        return _token_ok(self._auto, self._index.texts[token_id])


# ---------------------------------------------------------------------------
# on-device grammar FSM (ISSUE 7): grammar -> token-level DFA tables
# ---------------------------------------------------------------------------
#
# The host mask path above needs the previous token back on host before it
# can build the next mask — on tunneled links that is ~RTT per constrained
# token.  compile_tool_call_grammar() lowers the SAME automaton into three
# dense arrays a jitted decode step can consume with zero host round trips:
#
#   token_class [V] int32 — tokens partitioned into behavior classes (two
#       tokens share a class iff they behave identically from EVERY state;
#       class 0 is "illegal everywhere").  This is classic lexer-table
#       column compression: the full [S, V] transition matrix never
#       materializes — at a 128k vocab it would be gigabytes, while the
#       free-string bulk (the ~whole vocab, self-looping inside string
#       content) collapses into a handful of classes.
#   trans [S, C] int32 — state x class -> next state, -1 illegal.  The
#       per-lane allowed mask is `trans[state][token_class] >= 0`, and the
#       FSM advance after sampling is one [S, C] gather.
#   dist [S] int32 — shortest token-count from each state to `done`
#       (reverse BFS).  Near the token budget the device mask restricts to
#       distance-DECREASING transitions, the on-device analogue of the
#       host path's wrap-up mode: a bounded generation still parses.
#
# States are BFS-discovered automaton configurations, deduped by
# ToolCallAutomaton.signature(); per-state allowed sets come from
# allowed_ids_for() — the exact host-mask semantics — so the two paths
# accept identical token sets by construction.  Free-string states
# special-case the string_safe bulk as a self-loop (feeding quote-free
# safe characters never changes `in_str`), keeping the compile
# O(states x structural-tokens) instead of O(states x vocab).

GRAMMAR_ONDEVICE_ENV = "KAFKA_TPU_GRAMMAR_ONDEVICE"
GRAMMAR_TABLE_MB_ENV = "KAFKA_TPU_GRAMMAR_TABLE_MB"
_GRAMMAR_TABLE_MB_DEFAULT = 64
# BFS guard independent of the byte cap (a runaway grammar must fail the
# compile, not stall the process)
_GRAMMAR_MAX_STATES = 32768
# wrap-up engages when the remaining token budget is within this many
# tokens of the state's shortest close (mirrors ToolCallMaskFn's
# WRAP_UP_SLACK semantics at token granularity)
GRAMMAR_WRAP_SLACK = 4

_GRAMMAR_COMPILE_LOCK = __import__("threading").Lock()


def grammar_ondevice_enabled() -> bool:
    import os

    return os.environ.get(GRAMMAR_ONDEVICE_ENV, "1") not in (
        "0", "false", "off"
    )


def _grammar_table_cap_bytes() -> int:
    import os

    try:
        mb = float(os.environ.get(GRAMMAR_TABLE_MB_ENV, ""))
    except ValueError:
        mb = _GRAMMAR_TABLE_MB_DEFAULT
    if not mb:
        mb = _GRAMMAR_TABLE_MB_DEFAULT
    return int(mb * (1 << 20))


class CompiledGrammar:
    """Device-loadable token-level DFA for one (tools, tokenizer) pair.

    Immutable after compile; the engine registers it into its padded
    device table set (runtime/engine._GrammarTables) and lanes carry an
    int32 state advanced inside the jitted decode step.  State 0 is the
    initial state; `-1` is the engine's "unconstrained" sentinel and never
    appears in `trans`.
    """

    __slots__ = ("token_class", "trans", "dist", "num_states",
                 "num_classes", "vocab_size", "eot_id", "max_close_tokens",
                 "wrap_slack", "schema_key")

    def __init__(self, token_class, trans, dist, vocab_size, eot_id,
                 schema_key):
        self.token_class = token_class  # np [V] int32
        self.trans = trans              # np [S, C] int32, -1 illegal
        self.dist = dist                # np [S] int32 tokens-to-done
        self.num_states = trans.shape[0]
        self.num_classes = trans.shape[1]
        self.vocab_size = vocab_size
        self.eot_id = eot_id
        self.max_close_tokens = int(dist.max()) if dist.size else 0
        # Wrap-up window: the mask flips to distance-decreasing-only when
        # budget_left <= dist + wrap_slack.  For closure the window must
        # survive the largest one-token dist INCREASE any legal
        # transition can cause (a comma at a choice point commits the
        # generation to a whole forced `, "key": v` run): while wrap is
        # NOT engaged, budget > dist + slack, and after one token
        # dist' <= dist + max_jump, budget' = budget - 1 — so
        # slack >= max_jump + 1 guarantees budget' >= dist' at engagement
        # and the restriction then closes within budget.  The host mask
        # path keeps its fixed 4-char slack and CAN still strand a tight
        # budget mid-JSON on jump-heavy schemas; the compiled path is
        # strictly more robust here (wrap timing differs only in a regime
        # where neither path claims bit-identity).
        legal = self.trans >= 0
        if legal.any():
            nd = self.dist[np.clip(self.trans, 0, self.num_states - 1)]
            jump = np.where(legal, nd - self.dist[:, None], 0)
            self.wrap_slack = max(GRAMMAR_WRAP_SLACK, int(jump.max()) + 1)
        else:  # pragma: no cover — compile refuses empty grammars
            self.wrap_slack = GRAMMAR_WRAP_SLACK
        self.schema_key = schema_key

    @property
    def table_bytes(self) -> int:
        return int(
            self.token_class.nbytes + self.trans.nbytes + self.dist.nbytes
        )

    def allowed_row(
        self, state: int, budget_left: Optional[int] = None
    ) -> np.ndarray:
        """[V] bool mask for `state` (host-side: prefill masks, tests).

        With `budget_left` (remaining token budget INCLUDING the token
        this row masks) the device wrap-up rule applies: within
        GRAMMAR_WRAP_SLACK tokens of the state's shortest close, only
        distance-decreasing transitions stay allowed — the prefill-sampled
        token then obeys the same wrap-up the decode step enforces
        (ops/sampling.grammar_allowed_mask)."""
        if state < 0:
            return np.ones(self.vocab_size, bool)
        row = self.trans[state]
        keep = row >= 0
        if budget_left is not None and (
            budget_left <= int(self.dist[state]) + self.wrap_slack
        ):
            nd = self.dist[np.clip(row, 0, self.num_states - 1)]
            wrap_keep = keep & (nd < self.dist[state])
            if wrap_keep.any():
                keep = wrap_keep
        return keep[self.token_class]

    def walk(self, tokens: Sequence[int], start: int = 0) -> int:
        """Replay a token sequence host-side (resume after preemption).
        Returns -1 (unconstrained sentinel) if the history stops
        validating — the lane then degrades rather than crashing."""
        s = start
        for t in tokens:
            if s < 0:
                return -1
            t = int(t)
            if not (0 <= t < self.vocab_size):
                return -1
            s = int(self.trans[s, self.token_class[t]])
        return s


def compile_tool_call_grammar(
    tokenizer,
    tools: Sequence[Dict[str, Any]],
    force_name: Optional[str] = None,
    vocab_size: Optional[int] = None,
    max_table_bytes: Optional[int] = None,
) -> Optional[CompiledGrammar]:
    """Lower the tool-call grammar to device tables; None = fall back to
    the host mask path (table over the size cap, an over-tight state the
    tokenizer cannot express, or an eot outside the model vocab)."""
    index = TokenIndex.for_tokenizer(tokenizer)
    eot = int(tokenizer.eot_id)
    V = int(vocab_size if vocab_size is not None else tokenizer.vocab_size)
    if not (0 <= eot < V):
        return None
    cap = (
        max_table_bytes if max_table_bytes is not None
        else _grammar_table_cap_bytes()
    )
    try:
        auto0 = ToolCallAutomaton(tools, force_name=force_name)
    except ValueError:
        return None
    safe_set = {int(t) for t in index.string_safe if int(t) < V}

    states: List[ToolCallAutomaton] = [auto0]
    sig2idx: Dict[Tuple, int] = {auto0.signature(): 0}
    sparse: List[Dict[int, int]] = []   # per state: token id -> next state
    is_string: List[bool] = []          # free-string bulk self-loop flag
    i = 0
    while i < len(states):
        auto = states[i]
        edges: Dict[int, int] = {}
        sparse.append(edges)
        is_string.append(bool(auto.in_free_string))
        if auto.done:
            edges[eot] = i  # terminal self-loop; emission stops at eot
            i += 1
            continue
        allowed = allowed_ids_for(auto, index, eot)
        explicit = (
            [t for t in allowed if int(t) not in safe_set]
            if is_string[i] else allowed
        )
        if not allowed:
            # a reachable state the tokenizer cannot advance: the device
            # path could only degrade silently — refuse to compile
            return None
        for tid in explicit:
            tid = int(tid)
            if not (0 <= tid < V):
                continue
            nxt = auto.copy()
            ok = True
            for ch in index.texts[tid]:
                if not nxt.feed(ch):
                    ok = False
                    break
            if not ok:  # pragma: no cover — allowed_ids_for vetted it
                continue
            sig = nxt.signature()
            j = sig2idx.get(sig)
            if j is None:
                j = len(states)
                if j >= _GRAMMAR_MAX_STATES:
                    return None
                sig2idx[sig] = j
                states.append(nxt)
            edges[tid] = j
        i += 1

    S = len(states)
    # ---- column compression: token behavior classes -------------------
    # key = (sorted explicit (state, next) pairs, rides-string-bulk flag);
    # the [S, V] matrix is never materialized.
    cols: Dict[int, List[Tuple[int, int]]] = {}
    for s_idx, edges in enumerate(sparse):
        for tid, nxt in edges.items():
            cols.setdefault(tid, []).append((s_idx, nxt))
    string_states = [s for s, f in enumerate(is_string) if f]
    class_of: Dict[Tuple, int] = {}
    token_class = np.zeros(V, np.int32)  # class 0 = illegal everywhere
    class_cols: List[Tuple[Tuple[Tuple[int, int], ...], bool]] = [((), False)]
    for tid in range(V):
        in_bulk = tid in safe_set and string_states
        pairs = tuple(sorted(cols.get(tid, ())))
        if not pairs and not in_bulk:
            continue  # class 0
        key = (pairs, bool(in_bulk))
        c = class_of.get(key)
        if c is None:
            c = len(class_cols)
            class_of[key] = c
            class_cols.append(key)
        token_class[tid] = c
    C = len(class_cols)
    if (S * C + V + S) * 4 > cap:
        return None
    trans = np.full((S, C), -1, np.int32)
    for c, (pairs, in_bulk) in enumerate(class_cols):
        if in_bulk:
            for s_idx in string_states:
                trans[s_idx, c] = s_idx  # free-string self-loop
        for s_idx, nxt in pairs:
            trans[s_idx, c] = nxt
    # ---- shortest token-distance to done (reverse BFS) ----------------
    import collections as _c

    INF = 1 << 30
    dist = np.full(S, INF, np.int64)
    done_states = [s for s, a in enumerate(states) if a.done]
    rev: Dict[int, List[int]] = {}
    for s_idx in range(S):
        row = trans[s_idx]
        for nxt in set(int(n) for n in row[row >= 0]):
            if nxt != s_idx:
                rev.setdefault(nxt, []).append(s_idx)
    dq = _c.deque()
    for d0 in done_states:
        dist[d0] = 0
        dq.append(d0)
    while dq:
        cur = dq.popleft()
        for prev in rev.get(cur, ()):
            if dist[prev] > dist[cur] + 1:
                dist[prev] = dist[cur] + 1
                dq.append(prev)
    if (dist >= INF).any():
        # a state that cannot reach `done` would make wrap-up mask to
        # nothing; the grammar is malformed for on-device serving
        return None
    return CompiledGrammar(
        token_class, trans, dist.astype(np.int32), V, eot,
        schema_key=_grammar_schema_key(auto0, force_name, V),
    )


def _grammar_schema_key(auto: ToolCallAutomaton, force_name, V) -> Tuple:
    return (
        tuple(sorted(
            (name, tuple(props) if props is not None else None)
            for name, props in auto._props_by_name.items()
        )),
        force_name,
        V,
    )


# Per-tokenizer compile-cache bound: a long-lived server whose requests
# carry varying tool registries (MCP merges, per-request named
# tool_choice) must not grow host RSS one multi-hundred-KB artifact per
# distinct schema forever.  dict preserves insertion order; eviction
# drops the oldest entries (in-flight requests keep their artifact alive
# by reference — eviction only forgets the cache slot).
_GRAMMAR_CACHE_MAX = 16

# Deferred background compiles (ISSUE 9 satellite, PR 7 follow-up): the
# grammar->table BFS walks automaton x vocab, which on a real 128k-token
# vocab takes tens of seconds.  Blocking the FIRST agent call on an
# uncached large schema for that long (even off the event loop — the
# request itself stalls) is worse than serving it through the host mask
# path, so compiles for vocabs above KAFKA_TPU_GRAMMAR_SYNC_VOCAB run on
# a single background worker thread instead: the first call returns None
# (host path) immediately and later calls flip to on-device once the
# table lands in the cache.  Small vocabs (tests, the byte tokenizer)
# keep the synchronous path — their compiles are milliseconds.
GRAMMAR_SYNC_VOCAB_ENV = "KAFKA_TPU_GRAMMAR_SYNC_VOCAB"
_GRAMMAR_SYNC_VOCAB_DEFAULT = 32768

_DEFER_LOCK = __import__("threading").Lock()
_DEFER_PENDING: set = set()  # (id(tokenizer), schema key) being compiled
_DEFER_QUEUE: Optional[Any] = None  # queue.Queue, created with the worker


def compile_pending() -> int:
    """Gauge: grammar compiles queued/running on the background worker
    (exported as constrained_compile_pending in /metrics)."""
    return len(_DEFER_PENDING)


def _grammar_sync_vocab() -> int:
    import os

    try:
        return int(os.environ.get(GRAMMAR_SYNC_VOCAB_ENV, "") or
                   _GRAMMAR_SYNC_VOCAB_DEFAULT)
    except ValueError:
        return _GRAMMAR_SYNC_VOCAB_DEFAULT


def _compile_into_cache(tok, mask_fn, vocab_size: int, key) -> Optional[CompiledGrammar]:
    """The locked compile-and-cache step shared by the synchronous path
    and the background worker."""
    with _GRAMMAR_COMPILE_LOCK:
        cache = getattr(tok, "_grammar_cache", None)
        if cache is None:
            cache = {}
            try:
                tok._grammar_cache = cache
            except Exception:
                cache = None  # slotted tokenizer: compile per call
        if cache is not None and key in cache:
            return cache[key]
        g = compile_tool_call_grammar(
            tok, mask_fn.tools, force_name=mask_fn.force_name,
            vocab_size=vocab_size,
        )
        if cache is not None:
            while len(cache) >= _GRAMMAR_CACHE_MAX:
                cache.pop(next(iter(cache)))
            cache[key] = g  # negative results cached too
    return g


def _defer_worker() -> None:
    import logging
    import time as _time

    from ..runtime.autoscaler import background_deferred

    log = logging.getLogger("kafka_tpu.constrained")
    while True:
        tok, mask_fn, vocab_size, key = _DEFER_QUEUE.get()
        try:
            # overload degradation (autoscaler ladder rung 3): a grammar
            # compile is tens of seconds of host CPU the serving threads
            # need more — hold the queue until the overload clears (the
            # affected requests keep serving through the host mask path)
            while background_deferred():
                _time.sleep(0.25)
            _compile_into_cache(tok, mask_fn, vocab_size, key)
        except Exception as e:
            log.warning("deferred grammar compile failed: %s", e)
        finally:
            with _DEFER_LOCK:
                _DEFER_PENDING.discard((id(tok), key))


def _enqueue_deferred(tok, mask_fn, vocab_size: int, key) -> None:
    global _DEFER_QUEUE
    import queue as _queue
    import threading as _threading

    with _DEFER_LOCK:
        pkey = (id(tok), key)
        if pkey in _DEFER_PENDING:
            return  # one compile per schema, however many callers race
        _DEFER_PENDING.add(pkey)
        if _DEFER_QUEUE is None:
            _DEFER_QUEUE = _queue.Queue()
            _threading.Thread(
                target=_defer_worker, name="grammar-compile", daemon=True
            ).start()
    # the queue item holds a strong ref to tok, keeping id(tok) stable
    _DEFER_QUEUE.put((tok, mask_fn, vocab_size, key))


def compile_grammar_for_mask_fn(
    mask_fn, vocab_size: int, defer: Optional[bool] = None
) -> Optional[CompiledGrammar]:
    """Engine/provider hook: the on-device artifact for a ToolCallMaskFn
    request, or None (host fallback: disabled by env, a mask fn the
    compiler can't lower, a failed compile — all cached — or a large-
    vocab compile still in flight on the background worker).

    `defer` overrides the vocab-threshold policy (tests); None applies
    it: vocabs above KAFKA_TPU_GRAMMAR_SYNC_VOCAB compile in the
    background and this call returns None until the table lands."""
    if not grammar_ondevice_enabled():
        return None
    if not isinstance(mask_fn, ToolCallMaskFn):
        return None  # dynamic/custom mask fns keep the host micro-batch
    tok = mask_fn._tok
    key = _grammar_schema_key(mask_fn._auto, mask_fn.force_name, vocab_size)
    cache = getattr(tok, "_grammar_cache", None)
    if cache is not None and key in cache:
        return cache[key]
    if defer is None:
        defer = vocab_size > _grammar_sync_vocab()
    if defer:
        _enqueue_deferred(tok, mask_fn, vocab_size, key)
        return None  # host-mask path now; on-device once the table lands
    return _compile_into_cache(tok, mask_fn, vocab_size, key)


def build_tool_call_mask_fn(
    tokenizer,
    tools: Sequence[Dict[str, Any]],
    tool_choice: Any = "required",
) -> Optional[ToolCallMaskFn]:
    """Resolve an OpenAI-style tool_choice into a mask fn (None = don't).

    Only "required" and {"type": "function", "function": {"name": ...}}
    constrain; "auto"/"none"/None and unrecognized values return None.  A
    forced name that matches no declared tool degrades to unconstrained
    with a warning rather than failing the request.
    """
    if not tools:
        return None
    force = None
    if isinstance(tool_choice, dict):
        force = (tool_choice.get("function") or {}).get("name")
        declared = {
            (t.get("function", t)).get("name") for t in tools
        }
        if force not in declared:
            import logging

            logging.getLogger("kafka_tpu.constrained").warning(
                "tool_choice forces unknown function %r (declared: %s); "
                "falling back to unconstrained generation",
                force, sorted(n for n in declared if n),
            )
            return None
    elif tool_choice != "required":
        return None
    return ToolCallMaskFn(tokenizer, tools, force_name=force)


def validate_tool_call_json(
    text: str, tools: Sequence[Dict[str, Any]]
) -> bool:
    """Post-hoc check used by tests: parses, names a declared tool, and
    top-level parameter keys are declared properties."""
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        return False
    if not isinstance(obj, dict):
        return False
    by_name = {}
    for t in tools:
        fn = t.get("function", t)
        by_name[fn.get("name")] = fn.get("parameters") or {}
    if obj.get("name") not in by_name:
        return False
    params = obj.get("parameters")
    if not isinstance(params, dict):
        return False
    schema = by_name[obj["name"]]
    props = (schema.get("properties") or {}).keys()
    if props and schema.get("additionalProperties") is not True:
        return all(k in props for k in params)
    return True
