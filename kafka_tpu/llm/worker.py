"""Engine dispatch thread: bridges the synchronous TPU step loop to asyncio.

The InferenceEngine (runtime/engine.py) is synchronous by design — one
thread owns the device and runs admit/decode/retire steps.  The serving
layer is asyncio (like the reference's uvicorn event loop).  This module is
the seam: a single daemon thread drives the engine continuously while
requests and token events cross thread boundaries through queues.

Design (SURVEY §2.2 "host-side dispatch thread feeding the device loop"):

* `submit()` (any asyncio loop) → thread-safe inbox queue → picked up at the
  top of each engine step.
* Engine `TokenEvent`s → `loop.call_soon_threadsafe(asyncio.Queue.put_nowait)`
  into the per-request event queue, so each request's consumer wakes on its
  own loop with no polling.
* When idle, the thread blocks on the inbox (zero busy-wait); when active it
  drains the inbox without blocking between decode steps.

The single-writer design means engine state needs no locks — the dispatch
thread is the only mutator (SURVEY §5.2: the reference's hand-rolled
concurrency gaps are removed by construction).
"""

from __future__ import annotations

import asyncio
import logging
import queue
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from ..runtime.engine import AdmissionError, GenRequest, InferenceEngine, TokenEvent
from ..runtime.failpoints import failpoint
from ..runtime.tracing import add_event

logger = logging.getLogger("kafka_tpu.llm.worker")


@dataclass
class _Route:
    loop: asyncio.AbstractEventLoop
    events: "asyncio.Queue[TokenEvent]"
    # backpressure: tokens queued but not yet consumed (approximate)
    dropped: bool = field(default=False)


class EngineWorker:
    """Owns the engine thread; routes token events to per-request queues."""

    def __init__(self, engine: InferenceEngine):
        self.engine = engine
        self._inbox: "queue.Queue[Tuple[str, object]]" = queue.Queue()
        self._routes: Dict[str, _Route] = {}
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._lock = threading.Lock()  # guards _routes (submit vs dispatch)
        # pause seam (topology rebuilds): while paused the worker thread
        # parks between steps — the engine's single-writer invariant then
        # lets ANOTHER thread mutate engine structure safely
        self._pause_req = threading.Event()
        self._pause_ack = threading.Event()
        self._resume_evt = threading.Event()
        # terminal events whose dispatch failed, awaiting a paced retry
        # (worker-thread only; see _dispatch_guarded/_retry_redispatches)
        self._redispatches: list = []

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "EngineWorker":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="kafka-tpu-engine", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self._thread is None:
            return
        self._stopped.set()
        self._inbox.put(("__wake__", None))
        self._thread.join(timeout=timeout)
        self._thread = None

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def pause(self, timeout: float = 30.0) -> bool:
        """Park the engine thread between steps; returns once it is parked
        (True) or the wait timed out (False).  While paused, no step()
        runs and no inbox command is processed — the caller owns the
        engine and may restructure it (DataParallelEngines.rebuild).
        Always pair with resume(), promptly: submits and cancels queue up
        behind the pause."""
        if not self.alive:
            return True  # no thread -> nothing can race the caller
        self._resume_evt.clear()
        self._pause_ack.clear()
        self._pause_req.set()
        self._inbox.put(("__wake__", None))
        return self._pause_ack.wait(timeout)

    def resume(self) -> None:
        self._pause_req.clear()
        self._resume_evt.set()

    # -- request API (called from asyncio) -----------------------------

    def submit(
        self, req: GenRequest, loop: asyncio.AbstractEventLoop
    ) -> "asyncio.Queue[TokenEvent]":
        """Enqueue a request; returns the asyncio queue its events land on."""
        if self._stopped.is_set():
            raise RuntimeError("engine worker is stopped")
        events: "asyncio.Queue[TokenEvent]" = asyncio.Queue()
        with self._lock:
            self._routes[req.request_id] = _Route(loop=loop, events=events)
        self._inbox.put(("submit", req))
        return events

    def cancel(self, request_id: str) -> None:
        """Abort a request from the serving side (client disconnect)."""
        self._inbox.put(("cancel", request_id))

    def note_tool_gap(self, prefix_key: str) -> None:
        """Agent-native scheduling (ISSUE 20): the provider saw a lane
        finish with finish_reason=tool_calls — route the gap signal onto
        the engine thread (single-writer: all gap state lives there)."""
        self._inbox.put(("agent", ("gap", prefix_key)))

    def note_tool_return(self, prefix_key: str) -> None:
        """The thread's tool completed (sandbox SSE terminal): cancel a
        lingering demote or kick the return-prefetch, on the engine
        thread."""
        self._inbox.put(("agent", ("return", prefix_key)))

    # -- engine thread -------------------------------------------------

    def _run(self) -> None:
        logger.info("engine worker started")
        while not self._stopped.is_set():
            # pause seam: park between steps until resumed (or stopped)
            while self._pause_req.is_set() and not self._stopped.is_set():
                self._pause_ack.set()
                self._resume_evt.wait(timeout=0.1)
            # Block when idle; drain without blocking when active.
            block = not self.engine.has_work
            try:
                kind, payload = self._inbox.get(block=block, timeout=1.0 if block else None)
                self._handle(kind, payload)
                # drain any further queued commands
                while True:
                    try:
                        kind, payload = self._inbox.get_nowait()
                    except queue.Empty:
                        break
                    self._handle(kind, payload)
            except queue.Empty:
                pass
            if self._stopped.is_set():
                break
            # paced retry of parked terminal events: one attempt per loop
            # iteration (the blocking inbox get above bounds idle-engine
            # pacing at ~1s/round), placed before the idle `continue` so
            # an idle engine still drains its redispatch backlog
            self._retry_redispatches()
            if not self.engine.has_work:
                continue
            try:
                events = self.engine.step()
            except Exception:
                # Recovery ladder: rebuild a servable engine (fail started
                # requests, keep waiting ones, repair page accounting); if
                # recovery ITSELF dies, fall back to failing everything —
                # "every request gets a terminal event" must hold even
                # when the engine is beyond repair.
                logger.exception("engine step failed; recovering")
                try:
                    events = self.engine.recover_from_failure()
                except Exception:
                    logger.exception(
                        "engine recovery failed; failing all requests"
                    )
                    events = self._fail_all()
            for ev in events:
                self._dispatch_guarded(ev)
        logger.info("engine worker stopped")

    def _dispatch_guarded(self, ev: TokenEvent, attempts: int = 0) -> None:
        """Dispatch one event without letting a bad route (or an armed
        worker.dispatch failpoint) take down the worker loop or lose a
        terminal event.  Terminal events are load-bearing — a consumer
        awaits them forever — so a failed terminal dispatch is parked and
        retried once per loop iteration (_retry_redispatches paces the
        budget across real time, so bounded nth/count fault rules expire
        within it); when the budget is spent, a last-resort delivery runs
        with the failpoint bypassed — only a genuinely dead route loses
        its terminal event."""
        try:
            self._dispatch(ev)
        except Exception:
            logger.exception("event dispatch failed for %s", ev.request_id)
            if not ev.finished:
                return  # one lost token; the stream continues
            if attempts < 8:
                self._redispatches.append((ev, attempts + 1))
                return
            logger.error(
                "terminal event for %s still undeliverable after %d "
                "attempts; trying once more without fault injection",
                ev.request_id, attempts,
            )
            try:
                self._deliver(ev)
            except Exception:
                logger.exception(
                    "final delivery failed for %s; dropping its route",
                    ev.request_id,
                )
                with self._lock:
                    self._routes.pop(ev.request_id, None)

    def _retry_redispatches(self) -> None:
        """One retry round per loop iteration: each parked terminal event
        gets a single fresh attempt (re-parking itself on failure).  A
        list swap, not in-place iteration — _dispatch_guarded appends."""
        if not self._redispatches:
            return
        pending, self._redispatches = self._redispatches, []
        for ev, attempts in pending:
            self._dispatch_guarded(ev, attempts=attempts)

    def _handle(self, kind: str, payload: object) -> None:
        if kind == "submit":
            try:
                self.engine.submit(payload)  # type: ignore[arg-type]
            except AdmissionError as e:
                # queue-full backstop behind the server's admission gate
                # (the race where the queue fills between the gate's check
                # and this thread's submit): a distinct reason prefix so
                # the provider maps it to HTTP 429, not a 500
                req: GenRequest = payload  # type: ignore[assignment]
                logger.warning("submit rejected for %s: %s",
                               req.request_id, e)
                self._dispatch_guarded(
                    TokenEvent(
                        req.request_id, None, finished=True,
                        finish_reason=f"rejected:{e.retry_after_s:.0f}:{e}",
                    )
                )
            except Exception as e:  # surfaced to the consumer as an error event
                req = payload  # type: ignore[assignment]
                logger.warning("submit rejected for %s: %s", req.request_id, e)
                self._dispatch_guarded(
                    TokenEvent(
                        req.request_id, None, finished=True,
                        finish_reason=f"error:{e}",
                    )
                )
        elif kind == "agent":
            # ("gap"|"return", prefix_key) — the engine may be a single
            # InferenceEngine or a DataParallelEngines router (both
            # implement the note_tool_* pair); getattr keeps the worker
            # duck-typed against engine shims in tests
            verb, key = payload  # type: ignore[misc]
            fn = getattr(self.engine, f"note_tool_{verb}", None)
            if fn is not None:
                try:
                    fn(key)
                except Exception:  # an optimization must never kill steps
                    logger.exception("agent %s signal failed for %r",
                                     verb, key)
        elif kind == "cancel":
            rid: str = payload  # type: ignore[assignment]
            if self.engine.cancel(rid):
                self._dispatch_guarded(
                    TokenEvent(rid, None, finished=True, finish_reason="cancelled")
                )
            else:
                # request unknown/already done: just drop the route
                with self._lock:
                    self._routes.pop(rid, None)

    def _fail_all(self):
        """Device-step failure: every in-flight request gets a terminal event."""
        # recovery itself died: the flight recorder's ring is the only
        # artifact that will explain this engine — dump it before the
        # cancel sweep rewrites the lane table (best-effort, ISSUE 11)
        for e in getattr(self.engine, "engines", [self.engine]):
            try:
                dump = getattr(e, "dump_postmortem", None)
                if dump is not None:
                    dump("recovery_failed")
            except Exception:  # pragma: no cover - defensive
                logger.exception("recovery-failure postmortem dump failed")
        events = []
        for rid in list(self.engine._requests):
            req = self.engine._requests.get(rid)
            if req is not None:
                # recovery itself died: the trace still records why the
                # request ended (engine.recover_from_failure never ran
                # for these, so this is not a duplicate)
                add_event(req.trace, "engine.recover",
                          {"reason": "error:engine", "fail_all": True})
            # reason matches the event below so metrics count these as
            # engine failures (requests.failed), not client cancels
            self.engine.cancel(rid, reason="error:engine")
            events.append(
                TokenEvent(rid, None, finished=True, finish_reason="error:engine")
            )
        return events

    def check_routes(self) -> list:
        """Route-table consistency probe (chaos tests): ids with a live
        route but no engine-side request.  Call only at quiescence — a
        just-submitted request's route legitimately precedes its engine
        registration while the submit command sits in the inbox."""
        with self._lock:
            routed = list(self._routes)
        known = self.engine._requests
        return [rid for rid in routed if rid not in known]

    def _dispatch(self, ev: TokenEvent) -> None:
        failpoint("worker.dispatch")
        self._deliver(ev)

    def _deliver(self, ev: TokenEvent) -> None:
        """Route one event to its consumer queue (no fault injection —
        _dispatch_guarded's last-resort path calls this directly)."""
        with self._lock:
            route = self._routes.get(ev.request_id)
        if route is None:
            return
        try:
            route.loop.call_soon_threadsafe(route.events.put_nowait, ev)
        except RuntimeError:
            # consumer loop is gone (shutdown): cancel the request so the
            # engine doesn't decode into the void
            if not ev.finished and not route.dropped:
                route.dropped = True
                self._inbox.put(("cancel", ev.request_id))
        # the route is released only after the delivery attempt ran to
        # completion: an injected fault upstream must leave it intact so
        # the redispatch path can still deliver the terminal event
        if ev.finished:
            with self._lock:
                self._routes.pop(ev.request_id, None)
