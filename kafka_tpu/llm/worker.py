"""Engine dispatch thread: bridges the synchronous TPU step loop to asyncio.

The InferenceEngine (runtime/engine.py) is synchronous by design — one
thread owns the device and runs admit/decode/retire steps.  The serving
layer is asyncio (like the reference's uvicorn event loop).  This module is
the seam: a single daemon thread drives the engine continuously while
requests and token events cross thread boundaries through queues.

Design (SURVEY §2.2 "host-side dispatch thread feeding the device loop"):

* `submit()` (any asyncio loop) → thread-safe inbox queue → picked up at the
  top of each engine step.
* Engine `TokenEvent`s → `loop.call_soon_threadsafe(asyncio.Queue.put_nowait)`
  into the per-request event queue, so each request's consumer wakes on its
  own loop with no polling.
* When idle, the thread blocks on the inbox (zero busy-wait); when active it
  drains the inbox without blocking between decode steps.

The single-writer design means engine state needs no locks — the dispatch
thread is the only mutator (SURVEY §5.2: the reference's hand-rolled
concurrency gaps are removed by construction).
"""

from __future__ import annotations

import asyncio
import logging
import queue
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from ..runtime.engine import GenRequest, InferenceEngine, TokenEvent

logger = logging.getLogger("kafka_tpu.llm.worker")


@dataclass
class _Route:
    loop: asyncio.AbstractEventLoop
    events: "asyncio.Queue[TokenEvent]"
    # backpressure: tokens queued but not yet consumed (approximate)
    dropped: bool = field(default=False)


class EngineWorker:
    """Owns the engine thread; routes token events to per-request queues."""

    def __init__(self, engine: InferenceEngine):
        self.engine = engine
        self._inbox: "queue.Queue[Tuple[str, object]]" = queue.Queue()
        self._routes: Dict[str, _Route] = {}
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()
        self._lock = threading.Lock()  # guards _routes (submit vs dispatch)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "EngineWorker":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="kafka-tpu-engine", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self._thread is None:
            return
        self._stopped.set()
        self._inbox.put(("__wake__", None))
        self._thread.join(timeout=timeout)
        self._thread = None

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- request API (called from asyncio) -----------------------------

    def submit(
        self, req: GenRequest, loop: asyncio.AbstractEventLoop
    ) -> "asyncio.Queue[TokenEvent]":
        """Enqueue a request; returns the asyncio queue its events land on."""
        if self._stopped.is_set():
            raise RuntimeError("engine worker is stopped")
        events: "asyncio.Queue[TokenEvent]" = asyncio.Queue()
        with self._lock:
            self._routes[req.request_id] = _Route(loop=loop, events=events)
        self._inbox.put(("submit", req))
        return events

    def cancel(self, request_id: str) -> None:
        """Abort a request from the serving side (client disconnect)."""
        self._inbox.put(("cancel", request_id))

    # -- engine thread -------------------------------------------------

    def _run(self) -> None:
        logger.info("engine worker started")
        while not self._stopped.is_set():
            # Block when idle; drain without blocking when active.
            block = not self.engine.has_work
            try:
                kind, payload = self._inbox.get(block=block, timeout=1.0 if block else None)
                self._handle(kind, payload)
                # drain any further queued commands
                while True:
                    try:
                        kind, payload = self._inbox.get_nowait()
                    except queue.Empty:
                        break
                    self._handle(kind, payload)
            except queue.Empty:
                pass
            if self._stopped.is_set():
                break
            if not self.engine.has_work:
                continue
            try:
                events = self.engine.step()
            except Exception:
                logger.exception("engine step failed; failing active requests")
                events = self._fail_all()
            for ev in events:
                self._dispatch(ev)
        logger.info("engine worker stopped")

    def _handle(self, kind: str, payload: object) -> None:
        if kind == "submit":
            try:
                self.engine.submit(payload)  # type: ignore[arg-type]
            except Exception as e:  # surfaced to the consumer as an error event
                req: GenRequest = payload  # type: ignore[assignment]
                logger.warning("submit rejected for %s: %s", req.request_id, e)
                self._dispatch(
                    TokenEvent(
                        req.request_id, None, finished=True,
                        finish_reason=f"error:{e}",
                    )
                )
        elif kind == "cancel":
            rid: str = payload  # type: ignore[assignment]
            if self.engine.cancel(rid):
                self._dispatch(
                    TokenEvent(rid, None, finished=True, finish_reason="cancelled")
                )
            else:
                # request unknown/already done: just drop the route
                with self._lock:
                    self._routes.pop(rid, None)

    def _fail_all(self):
        """Device-step failure: every in-flight request gets a terminal event."""
        events = []
        for rid in list(self.engine._requests):
            self.engine.cancel(rid)
            events.append(
                TokenEvent(rid, None, finished=True, finish_reason="error:engine")
            )
        return events

    def _dispatch(self, ev: TokenEvent) -> None:
        with self._lock:
            route = self._routes.get(ev.request_id)
            if ev.finished:
                self._routes.pop(ev.request_id, None)
        if route is None:
            return
        try:
            route.loop.call_soon_threadsafe(route.events.put_nowait, ev)
        except RuntimeError:
            # consumer loop is gone (shutdown): cancel the request so the
            # engine doesn't decode into the void
            if not ev.finished and not route.dropped:
                route.dropped = True
                self._inbox.put(("cancel", ev.request_id))
