"""Image content-part decoding for the vision serving path.

The reference accepted OpenAI-wire image parts and forwarded them to
vision-capable provider models (src/llm/portkey.py:276 kept the newest 19
via utils.prune_images).  Here the parts are decoded locally — base64
data-URLs (and raw base64) to RGB pixel arrays sized for the ViT
(models/vision.py) — and each image part is replaced in the message text
by a single NUL sentinel character that the provider expands into
`num_patches` placeholder token ids after chat-template encoding.

The NUL sentinel is sound for the serving tokenizer (models/tokenizer.py
ByteTokenizer): NUL maps to byte token 0, and sentinelize_images STRIPS
any user-supplied NUL first (JSON's \\u0000 escape is legal, so incoming
text CAN carry one — unstripped it would collide with the sentinel and
let text pick where image embeddings land).  A subword checkpoint
tokenizer would instead use its own native image token (e.g. Llava's
<image>); the provider refuses vision + non-NUL-roundtripping tokenizers
at construction.
"""

from __future__ import annotations

import base64
import binascii
import io
from typing import Any, Dict, List, Tuple

import numpy as np

from ..core.types import LLMProviderError

IMAGE_SENTINEL = "\x00"


class ImageDecodeError(LLMProviderError):
    """Malformed image part (bad base64 / unsupported format) — a client
    error, mapped to HTTP 400 like other invalid_request errors."""

    def __init__(self, detail: str, provider: str = "tpu"):
        super().__init__(
            f"could not decode image: {detail} (invalid_request_error)",
            status_code=400, provider=provider,
        )


def _image_url_of(part: Dict[str, Any]) -> str:
    if part.get("type") == "image_url":
        url = part.get("image_url")
        if isinstance(url, dict):
            url = url.get("url")
        return url or ""
    # Anthropic-style {"type": "image", "source": {"data": ..}} passthrough
    src = part.get("source") or {}
    return src.get("data") or part.get("data") or ""


def decode_image(part: Dict[str, Any], image_size: int) -> np.ndarray:
    """One OpenAI-wire image part -> [S, S, 3] float32 in [0, 1]."""
    from PIL import Image

    url = _image_url_of(part)
    if not url:
        raise ImageDecodeError("image part carries no data")
    if url.startswith("data:"):
        try:
            _, b64 = url.split(",", 1)
        except ValueError:
            raise ImageDecodeError("malformed data URL")
    elif url.startswith(("http://", "https://")):
        raise ImageDecodeError(
            "remote image URLs are not fetched (no egress from the "
            "serving tier); send a base64 data URL"
        )
    else:
        b64 = url
    try:
        raw = base64.b64decode(b64, validate=True)
        img = Image.open(io.BytesIO(raw)).convert("RGB")
    except (binascii.Error, ValueError, OSError) as e:
        raise ImageDecodeError(str(e))
    img = img.resize((image_size, image_size), Image.BILINEAR)
    return np.asarray(img, np.float32) / 255.0


def sentinelize_images(
    messages: List[Dict[str, Any]],
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Replace each image part with the NUL sentinel text part; return
    (rewritten messages, the original image parts in document order).
    Decode-free — count_prompt_tokens uses this to price a prompt without
    touching pixels.

    User-supplied NUL characters are STRIPPED from every text first: JSON
    forbids raw control bytes but allows the \\u0000 escape, so without
    this an attacker-chosen text NUL would collide with the sentinel and
    bind the image embeddings to a position the text picked."""

    def clean(s: Any) -> Any:
        return s.replace(IMAGE_SENTINEL, "") if isinstance(s, str) else s

    out: List[Dict[str, Any]] = []
    image_parts: List[Dict[str, Any]] = []
    for m in messages:
        c = m.get("content")
        if isinstance(c, str):
            if IMAGE_SENTINEL in c:
                m = {**m, "content": clean(c)}
            out.append(m)
            continue
        if not isinstance(c, list):
            out.append(m)
            continue
        parts: List[Any] = []
        changed = False
        for p in c:
            if isinstance(p, dict) and p.get("type") in ("image_url", "image"):
                image_parts.append(p)
                parts.append({"type": "text", "text": IMAGE_SENTINEL})
                changed = True
            elif (isinstance(p, dict) and p.get("type") == "text"
                  and IMAGE_SENTINEL in (p.get("text") or "")):
                parts.append({**p, "text": clean(p["text"])})
                changed = True
            else:
                parts.append(p)
        if changed:
            m = {**m, "content": parts}
        out.append(m)
    return out, image_parts


def extract_images(
    messages: List[Dict[str, Any]], image_size: int
) -> Tuple[List[Dict[str, Any]], List[np.ndarray]]:
    """sentinelize + decode: (rewritten messages, pixel arrays)."""
    out, parts = sentinelize_images(messages)
    return out, [decode_image(p, image_size) for p in parts]


def expand_placeholders(
    prompt_ids: List[int],
    sentinel_id: int,
    image_token_id: int,
    num_patches: int,
    n_images: int,
) -> Tuple[List[int], np.ndarray]:
    """Expand each sentinel token into `num_patches` placeholder ids.

    Returns (new ids, [n_images * num_patches] absolute positions of the
    placeholder tokens, image-major in document order — exactly the rows
    the vision encoder produced)."""
    ids: List[int] = []
    positions: List[int] = []
    seen = 0
    for t in prompt_ids:
        if t == sentinel_id and seen < n_images:
            positions.extend(range(len(ids), len(ids) + num_patches))
            ids.extend([image_token_id] * num_patches)
            seen += 1
        else:
            ids.append(t)
    if seen != n_images:
        raise ImageDecodeError(
            f"placeholder mismatch: {n_images} images but {seen} "
            "sentinels survived tokenization"
        )
    return ids, np.asarray(positions, np.int32)
