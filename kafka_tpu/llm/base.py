"""LLM provider abstraction: streaming-first, tool-aware.

Capability parity with the reference provider ABC
(reference: src/llm/base.py:67-312 — `stream_completion`, `completion`,
`validate_messages`, `get_model_info`), async-first like the reference.
The central difference: implementations here are expected to be *local*
(the TPU engine), so errors like context overflow are typed and raised
pre-flight instead of string-matched out of a remote gateway's response.
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator, Dict, List, Optional, Sequence, Union

from ..core.types import (
    CompletionResponse,
    LLMProviderError,
    Message,
    StreamChunk,
)

MessageLike = Union[Message, Dict[str, Any]]

VALID_ROLES = {"system", "user", "assistant", "tool", "developer"}


def to_message_dicts(messages: Sequence[MessageLike]) -> List[Dict[str, Any]]:
    """Normalize a mixed Message/dict list to OpenAI-wire dicts."""
    out: List[Dict[str, Any]] = []
    for m in messages:
        out.append(m.to_dict() if isinstance(m, Message) else dict(m))
    return out


class LLMProvider(abc.ABC):
    """Abstract LLM provider.

    Implementations must provide `stream_completion`; `completion` has a
    default implementation that drains the stream (mirroring how the
    reference agent always streams internally, src/agents/base.py:222).
    """

    #: provider family name, used in error messages and routing
    provider_name: str = "base"

    def build_tool_call_mask_fn(
        self,
        tools: Optional[List[Dict[str, Any]]],
        tool_choice: Any = "required",
    ):
        """Optional constrained-decoding hook (BASELINE config 4).

        Providers with a local sampler return a `logits_mask_fn` that
        forces generations to be schema-valid tool-call JSON; remote/
        text-only providers return None and callers fall back to free
        generation.
        """
        return None

    @abc.abstractmethod
    def stream_completion(
        self,
        messages: Sequence[MessageLike],
        model: Optional[str] = None,
        temperature: float = 0.7,
        max_tokens: Optional[int] = None,
        tools: Optional[List[Dict[str, Any]]] = None,
        **kwargs: Any,
    ) -> AsyncIterator[StreamChunk]:
        """Stream a chat completion as incremental `StreamChunk`s.

        Must yield a first chunk carrying `role="assistant"`, then content /
        tool-call deltas, then exactly one final chunk with `finish_reason`
        set (and `usage` populated, which the reference could not do on
        streaming paths — src/kafka/types.py:93-97 returned zeros).
        """
        raise NotImplementedError

    async def completion(
        self,
        messages: Sequence[MessageLike],
        model: Optional[str] = None,
        temperature: float = 0.7,
        max_tokens: Optional[int] = None,
        tools: Optional[List[Dict[str, Any]]] = None,
        **kwargs: Any,
    ) -> CompletionResponse:
        """Non-streaming completion; default drains `stream_completion`."""
        from ..core.toolcalls import ToolCallAccumulator

        content_parts: List[str] = []
        acc = ToolCallAccumulator()
        finish_reason: Optional[str] = None
        usage: Optional[Dict[str, int]] = None
        resp_model: Optional[str] = model
        resp_id: Optional[str] = None
        async for chunk in self.stream_completion(
            messages,
            model=model,
            temperature=temperature,
            max_tokens=max_tokens,
            tools=tools,
            **kwargs,
        ):
            if chunk.content:
                content_parts.append(chunk.content)
            acc.add_deltas(chunk.tool_calls)
            if chunk.finish_reason is not None:
                finish_reason = chunk.finish_reason
            if chunk.usage is not None:
                usage = chunk.usage
            if chunk.model:
                resp_model = chunk.model
            if chunk.id:
                resp_id = chunk.id
        tool_calls = acc.result() if acc.has_calls else None
        return CompletionResponse(
            content="".join(content_parts) if content_parts else None,
            role="assistant",
            finish_reason=finish_reason or "stop",
            model=resp_model,
            id=resp_id,
            usage=usage,
            tool_calls=tool_calls,
        )

    # ------------------------------------------------------------------

    def validate_messages(self, messages: Sequence[MessageLike]) -> None:
        """Structural validation before hitting the engine.

        Parity: reference src/llm/base.py:221-312 (role checks, tool linkage).
        Raises LLMProviderError on the first violation.
        """
        if not messages:
            raise LLMProviderError(
                "messages must not be empty", provider=self.provider_name
            )
        dicts = to_message_dicts(messages)
        open_ids: set = set()
        for i, m in enumerate(dicts):
            role = m.get("role")
            if role not in VALID_ROLES:
                raise LLMProviderError(
                    f"message {i}: invalid role {role!r}",
                    provider=self.provider_name,
                )
            if role == "tool":
                tcid = m.get("tool_call_id")
                if not tcid:
                    raise LLMProviderError(
                        f"message {i}: tool message missing tool_call_id",
                        provider=self.provider_name,
                    )
                if tcid not in open_ids:
                    raise LLMProviderError(
                        f"message {i}: tool message answers unknown "
                        f"tool_call_id {tcid!r} (sanitize history first)",
                        provider=self.provider_name,
                    )
                open_ids.discard(tcid)
            elif role == "assistant" and m.get("tool_calls"):
                open_ids = {
                    tc.get("id") for tc in m["tool_calls"] if tc.get("id")
                }
            else:
                open_ids = set()

    def get_model_info(self, model: Optional[str] = None) -> Dict[str, Any]:
        """Metadata about a served model (id, context window, provider)."""
        return {"id": model, "provider": self.provider_name}

    def get_available_models(self) -> List[Dict[str, Any]]:
        """List models this provider can serve (for GET /v1/models)."""
        return []

    async def aclose(self) -> None:
        """Release resources (dispatch threads, device memory refs)."""
