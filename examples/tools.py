"""Example tool definitions for the library demos.

Parity with reference examples/tools.py:106-161 — a live-API tool, a
streaming demo tool, and how custom tools are declared.  Reuses the
built-ins the server ships (server_tools/) rather than duplicating them.
"""

import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kafka_tpu.server_tools.counter import counter_tool
from kafka_tpu.server_tools.weather import weather_tool
from kafka_tpu.tools.types import Tool


def make_example_tools() -> List[Tool]:
    """Weather (live Open-Meteo when network allows), a streaming counter,
    and a trivial custom tool showing the handler contract."""

    def shout(text: str = "") -> str:
        return text.upper() + "!"

    return [
        weather_tool(),
        counter_tool(),
        Tool(
            name="shout",
            description="Uppercase the given text (demo of a custom tool).",
            parameters={
                "type": "object",
                "properties": {"text": {"type": "string"}},
                "required": ["text"],
            },
            handler=shout,
        ),
    ]
