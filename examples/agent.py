"""Library-usage demos: run the Kafka agent in-process, no server needed.

Parity with reference examples/agent.py:34-156 (stateless run + thread
run), re-targeted at the local TPU stack: instead of a remote gateway the
LLM is the in-tree engine serving a tiny random-weight model, so the demo
runs anywhere (CPU included) with zero credentials and zero network.

    python examples/agent.py            # stateless agent run
    python examples/agent.py --thread   # thread-persistent run (SQLite)

With a real checkpoint directory (HF layout), point the provider at it:
    KAFKA_TPU_CHECKPOINT=/path/to/llama python examples/agent.py
"""

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples.tools import make_example_tools  # noqa: E402
from kafka_tpu.db.local import LocalDBClient  # noqa: E402
from kafka_tpu.kafka.v1 import KafkaV1Provider  # noqa: E402
from kafka_tpu.llm import TPULLMProvider  # noqa: E402
from kafka_tpu.models import get_config, init_params  # noqa: E402
from kafka_tpu.models.tokenizer import ByteTokenizer  # noqa: E402
from kafka_tpu.runtime import EngineConfig, InferenceEngine  # noqa: E402


def make_local_llm() -> TPULLMProvider:
    """An in-process LLM provider over the continuous-batching engine."""
    import jax

    cfg = get_config("tiny-gqa")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tok = ByteTokenizer()
    engine = InferenceEngine(
        cfg, params,
        EngineConfig(max_batch=4, page_size=16, num_pages=1200,
                     max_pages_per_seq=256, prefill_buckets=(64, 256, 1024,
                                                             4096)),
    )
    return TPULLMProvider(engine, tok, model_name=cfg.name)


def print_event(event: dict) -> None:
    """Render the agent event protocol the way a console client would."""
    etype = event.get("type")
    if event.get("object") == "chat.completion.chunk":
        delta = (event.get("choices") or [{}])[0].get("delta", {})
        if delta.get("content"):
            print(delta["content"], end="", flush=True)
        for tc in delta.get("tool_calls") or []:
            fn = tc.get("function", {})
            if fn.get("name"):
                print(f"\n[tool call] {fn['name']}", flush=True)
    elif etype == "tool_result":
        if event.get("delta"):
            print(f"  | {event['delta']}", end="", flush=True)
        if event.get("done"):
            print()
    elif etype == "agent_done":
        print(f"\n-- agent done ({event.get('reason')})")
    elif etype == "error":
        print(f"\n!! error: {event.get('error')}")


async def run_stateless() -> None:
    """One-shot agent run: no thread, no persistence."""
    kafka = KafkaV1Provider(
        make_local_llm(),
        tools=make_example_tools(),
        system_prompt=(
            "You are a helpful agent. Use tools when asked about weather "
            "or counting; call idle when finished."
        ),
    )
    await kafka.initialize()
    try:
        print("user: what's the weather in Tokyo?\n")
        async for event in kafka.run(
            [{"role": "user", "content": "what's the weather in Tokyo?"}],
            temperature=0.7,
            max_tokens=64,
        ):
            print_event(event)
    finally:
        await kafka.cleanup()


async def run_with_thread() -> None:
    """Thread-persistent run: history survives across runs via SQLite."""
    db = LocalDBClient("data/examples_threads.db")
    await db.initialize()
    thread_id = "example-thread-1"
    kafka = KafkaV1Provider(
        make_local_llm(),
        thread_db=db,
        tools=make_example_tools(),
        thread_id=thread_id,
        system_prompt="You are a helpful agent. Call idle when finished.",
    )
    await kafka.initialize()
    try:
        for turn, text in enumerate(
            ["remember the number 42", "what number did I ask you to remember?"]
        ):
            print(f"\nuser: {text}\n")
            async for event in kafka.run_with_thread(
                thread_id,
                [{"role": "user", "content": text}],
                temperature=0.7,
                max_tokens=48,
            ):
                print_event(event)
        history = await db.get_thread_messages(thread_id)
        print(f"\nthread {thread_id!r} now holds {len(history)} messages")
    finally:
        await kafka.cleanup()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--thread", action="store_true",
                    help="thread-persistent demo instead of stateless")
    args = ap.parse_args()
    asyncio.run(run_with_thread() if args.thread else run_stateless())
